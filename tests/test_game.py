"""GAME layer tests: coordinate semantics, residual descent, estimator.

Reference analogs: FixedEffectCoordinateIntegTest, RandomEffectCoordinateIntegTest,
GameEstimatorIntegTest (SURVEY.md §4).
"""

import numpy as np
import pytest

from photon_ml_tpu.core.regularization import Regularization
from photon_ml_tpu.evaluation import EvaluationSuite
from photon_ml_tpu.game import (
    CoordinateDescent,
    FixedEffectConfig,
    GameData,
    GameEstimator,
    GameTransformer,
    RandomEffectConfig,
    build_coordinate,
)
from photon_ml_tpu.game.config import GameConfig
from photon_ml_tpu.models.game import GameModel
from photon_ml_tpu.opt.types import SolverConfig
from photon_ml_tpu.types import TaskType


def _glmix_data(rng, n_users=20, per_user=60, d_global=6, d_user=3):
    """Generative GLMix: logit = x_g·w_g + x_u·w_user(u)."""
    n = n_users * per_user
    xg = rng.normal(size=(n, d_global))
    xu = rng.normal(size=(n, d_user))
    uid = np.repeat(np.arange(n_users) * 3 + 11, per_user)
    wg = rng.normal(size=d_global) * 0.8
    wu = rng.normal(size=(n_users, d_user)) * 1.2
    logits = xg @ wg + np.einsum("nd,nd->n", xu, wu[np.repeat(np.arange(n_users), per_user)])
    y = (rng.random(n) < 1.0 / (1.0 + np.exp(-logits))).astype(float)
    data = GameData(
        y=y,
        features={"global": xg, "per_user": xu},
        id_tags={"userId": uid},
    )
    return data, wg, wu, logits


def _configs(num_iters=3):
    solver = SolverConfig(max_iters=100, tolerance=1e-8)
    return GameConfig(
        task=TaskType.LOGISTIC_REGRESSION,
        coordinates={
            "fixed": FixedEffectConfig(feature_shard="global", solver=solver,
                                       reg=Regularization(l2=1.0)),
            "per-user": RandomEffectConfig(random_effect_type="userId",
                                           feature_shard="per_user", solver=solver,
                                           reg=Regularization(l2=1.0)),
        },
        num_outer_iterations=num_iters,
    )


def test_fixed_coordinate_update_and_score(rng):
    data, wg, _, _ = _glmix_data(rng, n_users=4, per_user=50)
    cfg = _configs().coordinates["fixed"]
    coord = build_coordinate("fixed", data, cfg, TaskType.LOGISTIC_REGRESSION)
    model, res = coord.update(np.zeros(data.num_samples))
    s = coord.score(model)
    np.testing.assert_allclose(
        s, data.features["global"] @ model.coefficients.means, rtol=1e-5, atol=1e-6
    )



def test_residual_offsets_matter(rng):
    """A coordinate trained with the other coordinate's score as offset must
    differ from one trained without (the residual trick)."""
    data, *_ = _glmix_data(rng, n_users=4, per_user=50)
    cfg = _configs().coordinates["fixed"]
    coord = build_coordinate("fixed", data, cfg, TaskType.LOGISTIC_REGRESSION)
    m0, _ = coord.update(np.zeros(data.num_samples))
    m1, _ = coord.update(rng.normal(size=data.num_samples) * 2.0)
    assert not np.allclose(m0.coefficients.means, m1.coefficients.means)


def test_glmix_descent_beats_fixed_only(rng):
    data, wg, wu, logits = _glmix_data(rng)
    suite = EvaluationSuite.from_specs(["auc", "logistic_loss"], primary="auc")
    est = GameEstimator(validation_suite=suite)
    # fixed-only
    fixed_only = GameConfig(
        task=TaskType.LOGISTIC_REGRESSION,
        coordinates={"fixed": _configs().coordinates["fixed"]},
    )
    r_fixed = est.fit(data, [fixed_only], validation_data=data)[0]
    # full GLMix
    r_full = est.fit(data, [_configs()], validation_data=data)[0]
    auc_fixed = r_fixed.evaluation.values["auc"]
    auc_full = r_full.evaluation.values["auc"]
    assert auc_full > auc_fixed + 0.05, (auc_fixed, auc_full)
    assert auc_full > 0.8


def test_glmix_recovers_fixed_coefficients(rng):
    """With random effects absorbing per-user structure, the fixed coordinate
    should approach the generative global coefficients."""
    data, wg, wu, _ = _glmix_data(rng, n_users=30, per_user=80)
    res = GameEstimator().fit(data, [_configs(num_iters=4)])[0]
    w_hat = res.model["fixed"].coefficients.means
    corr = np.corrcoef(w_hat, wg)[0, 1]
    assert corr > 0.95, corr


def test_descent_converges_training_loss(rng):
    """Each outer iteration must not worsen the training objective.
    fused=False: the per-update validation entries this asserts live in the
    HOST loop's history (the fused validated program tracks per-update
    losses in-program instead — tests/test_solve_path.py)."""
    data, *_ = _glmix_data(rng, n_users=8, per_user=40)
    suite = EvaluationSuite.from_specs(["logistic_loss"])
    est = GameEstimator(validation_suite=suite, fused=False)
    res = est.fit(data, [_configs(num_iters=3)], validation_data=data)[0]
    losses = [s["validation"].values["logistic_loss"] for s in res.history.steps]
    assert losses[-1] <= losses[0]
    # best-model tracking returned the minimum seen
    assert res.evaluation.values["logistic_loss"] <= min(losses) + 1e-9


def test_normalization_returns_original_space_model(rng):
    """A standardized solve must publish ORIGINAL-space coefficients: with
    negligible regularization the optimum is normalization-invariant, so the
    published models must agree (NormalizationContext.scala:73-124 parity)."""
    import jax.numpy as jnp

    from photon_ml_tpu.core.normalization import (build_normalization,
                                                  compute_feature_stats)
    from photon_ml_tpu.core.regularization import Regularization
    from photon_ml_tpu.game.config import FixedEffectConfig
    from photon_ml_tpu.types import NormalizationType

    n, d = 600, 4
    # badly scaled features (bad conditioning, margins still O(1))
    scales = np.asarray([100.0, 0.01, 5.0, 1.0])
    x = rng.normal(size=(n, d)) * scales + np.asarray([10.0, 0.0, 0.0, 2.0])
    x = np.concatenate([x, np.ones((n, 1))], axis=1)  # intercept col 4
    w_true = np.asarray([0.01, 60.0, -0.2, 0.8, 0.5])
    y = (rng.random(n) < 1 / (1 + np.exp(-(x @ w_true)))).astype(np.float64)
    data = GameData(features={"s": x}, y=y, offset=np.zeros(n), weight=np.ones(n),
                    id_tags={})

    def fit(norm):
        cfg = GameConfig(task=TaskType.LOGISTIC_REGRESSION, coordinates={
            "fixed": FixedEffectConfig(feature_shard="s",
                                       reg=Regularization(l2=1e-6),
                                       intercept_index=4)})
        est = GameEstimator(normalization=norm)
        return est.fit(data, [cfg])[0].model["fixed"].coefficients.means

    stats = compute_feature_stats(jnp.asarray(x), jnp.asarray(np.ones(n)),
                                  intercept_index=4)
    ctx = build_normalization(NormalizationType.STANDARDIZATION, stats)
    w_plain = fit(None)
    w_norm = fit({"s": ctx})
    # the published coefficients are ORIGINAL-space: they recover the
    # generative weights (including the tiny-scale feature's w=60 that the
    # unnormalized solve cannot move within its iteration budget)
    np.testing.assert_allclose(w_norm, w_true, rtol=0.25, atol=0.5)

    def logloss(w):
        z = np.clip(x @ w, -30, 30)
        return float(np.mean(np.log1p(np.exp(-np.abs(z))) + np.maximum(z, 0) - z * y))

    # conditioning win: the normalized solve reaches a better optimum
    assert logloss(w_norm) < logloss(w_plain) - 0.01, (logloss(w_norm), logloss(w_plain))


def test_checkpoint_resume_matches_uninterrupted(rng):
    """Preemption mid-descent: resuming from the captured (model, cursor)
    reproduces the uninterrupted run exactly (storage/checkpoint wiring)."""
    data, *_ = _glmix_data(rng, n_users=6, per_user=40)
    est = GameEstimator()
    cfg = _configs(num_iters=3)

    states = []
    full = est.fit(data, [cfg],
                   checkpoint_hook=lambda m, cur, **kw: states.append((m, cur)))[0]
    assert len(states) == 3 * len(cfg.coordinates)
    assert states[0][1] == {"config": 0, "iteration": 0, "coordinate": 1}

    # "crash" after the 3rd update; resume from that checkpoint
    model_ck, cursor_ck = states[2]
    resumed = est.fit(data, [cfg], initial_model=model_ck,
                      resume_cursor=cursor_ck)[0]
    # resume rebuilds `total` as a fresh sum while the uninterrupted run
    # accumulated it incrementally — f32 ordering noise only
    np.testing.assert_allclose(resumed.model["fixed"].coefficients.means,
                               full.model["fixed"].coefficients.means, atol=2e-3)
    for cid in cfg.coordinates:
        if cid != "fixed":
            np.testing.assert_allclose(np.asarray(resumed.model[cid].w_stack),
                                       np.asarray(full.model[cid].w_stack), atol=2e-3)


def test_checkpoint_preserves_best_model_across_resume(rng):
    """Best-by-primary-metric retention must survive preemption: the hook
    captures (best, best_changed) and resume seeds the tracker with it."""
    data, *_ = _glmix_data(rng, n_users=6, per_user=40)
    suite = EvaluationSuite.from_specs(["auc", "logistic_loss"], primary="auc")
    est = GameEstimator(validation_suite=suite)
    cfg = _configs(num_iters=3)

    snaps = []
    full = est.fit(data, [cfg], validation_data=data,
                   checkpoint_hook=lambda m, cur, **kw: snaps.append((m, cur, kw)))[0]
    # best-model retention compares FULL models only (reference
    # CoordinateDescent.scala:163-167): snapshots before the first complete
    # sweep carry no best; every one after the first sweep does
    n_coords = len(cfg.coordinates)
    assert all(kw["best"] is None for _, _, kw in snaps[: n_coords - 1])
    assert all(kw["best"] is not None for _, _, kw in snaps[n_coords - 1:])
    # first save of a config is a FULL snapshot (no stale hard-link baseline);
    # later saves are incremental with the updated coordinate named
    assert snaps[0][2]["updated"] is None
    assert snaps[1][2]["updated"] is not None
    m_ck, cur_ck, kw_ck = snaps[2]
    resumed = est.fit(data, [cfg], validation_data=data, initial_model=m_ck,
                      resume_cursor=cur_ck, resume_best=kw_ck["best"])[0]
    # the resumed run may only return something at least as good as the
    # checkpointed best (it can improve later, never regress below it)
    assert resumed.evaluation.values["auc"] >= kw_ck["best"][1].primary - 1e-9


def test_warm_start_and_locked_coordinates(rng):
    data, *_ = _glmix_data(rng, n_users=6, per_user=40)
    est = GameEstimator()
    first = est.fit(data, [_configs(num_iters=2)])[0]
    # partial retrain: lock the fixed effect, retrain only random effects
    res = est.fit(data, [_configs(num_iters=1)], initial_model=first.model,
                  locked_coordinates={"fixed"})[0]
    np.testing.assert_array_equal(
        res.model["fixed"].coefficients.means, first.model["fixed"].coefficients.means
    )
    # locked without initial model -> error
    with pytest.raises(ValueError, match="locked"):
        est.fit(data, [_configs(num_iters=1)], locked_coordinates={"fixed"})


def test_transformer_scores_new_data(rng):
    full, wg, wu, _ = _glmix_data(rng, per_user=80)
    n = full.num_samples
    idx = rng.permutation(n)
    tr, te = idx[: n // 2], idx[n // 2:]

    def take(i):
        return GameData(
            y=full.y[i],
            features={k: v[i] for k, v in full.features.items()},
            id_tags={k: v[i] for k, v in full.id_tags.items()},
        )

    data, new_data = take(tr), take(te)
    res = GameEstimator().fit(data, [_configs(num_iters=2)])[0]
    tf = GameTransformer(res.model, TaskType.LOGISTIC_REGRESSION)
    scores = tf.score(new_data)
    assert scores.shape == (new_data.num_samples,)
    preds = tf.predict(new_data)
    assert np.all((preds >= 0) & (preds <= 1))
    suite = EvaluationSuite.from_specs(["auc"])
    ev = tf.evaluate(new_data, suite)
    assert ev.values["auc"] > 0.6  # generalizes (same users, new samples)


def test_grouped_validation_metric(rng):
    data, *_ = _glmix_data(rng, n_users=6, per_user=50)
    suite = EvaluationSuite.from_specs(["auc", "auc:userId"], primary="auc")
    est = GameEstimator(validation_suite=suite)
    res = est.fit(data, [_configs(num_iters=1)], validation_data=data)[0]
    assert "auc:userId" in res.evaluation.values
    assert 0.0 <= res.evaluation.values["auc:userId"] <= 1.0


def test_multiple_configs_warm_start(rng):
    """Reg-path over two configs: second fit warm-starts from the first."""
    data, *_ = _glmix_data(rng, n_users=5, per_user=40)
    suite = EvaluationSuite.from_specs(["auc"])
    est = GameEstimator(validation_suite=suite)
    c1 = _configs(num_iters=1)
    results = est.fit(data, [c1, c1], validation_data=data)
    assert len(results) == 2
    best = est.best(results)
    assert best in results


def test_down_sampling_weights_semantics(rng):
    """Reference BinaryClassificationDownSampler.scala:32-55: keep every
    positive at weight 1, keep negatives with prob=rate at weight 1/rate,
    drop the rest (weight 0); deterministic per seed; rate>=1 is a no-op."""
    import dataclasses

    data, _, _, _ = _glmix_data(rng, n_users=8, per_user=40)
    cfg = FixedEffectConfig(feature_shard="global",
                            solver=SolverConfig(max_iters=20),
                            reg=Regularization(l2=1.0), down_sampling_rate=0.5)
    coord = build_coordinate("fixed", data, cfg, TaskType.LOGISTIC_REGRESSION)

    base = np.asarray(coord._base_weight)
    w = np.asarray(coord._down_sample_weights(seed=7))
    y = np.asarray(coord._batch.y)

    pos = y > 0.5
    np.testing.assert_allclose(w[pos], base[pos])  # positives untouched
    neg = ~pos & (base > 0)  # padded rows have base weight 0
    kept = neg & (w > 0)
    dropped = neg & (w == 0)
    assert kept.sum() > 0 and dropped.sum() > 0
    np.testing.assert_allclose(w[kept], base[kept] / 0.5)
    # survivor mass ~= original negative mass in expectation
    assert abs(w[neg].sum() - base[neg].sum()) / base[neg].sum() < 0.25
    # deterministic per seed, different across seeds
    np.testing.assert_array_equal(w, np.asarray(coord._down_sample_weights(seed=7)))
    assert not np.array_equal(w, np.asarray(coord._down_sample_weights(seed=8)))

    # rate >= 1 is the identity
    full = build_coordinate(
        "fixed", data,
        dataclasses.replace(cfg, down_sampling_rate=1.0),
        TaskType.LOGISTIC_REGRESSION)
    np.testing.assert_array_equal(np.asarray(full._down_sample_weights(seed=7)),
                                  np.asarray(full._base_weight))

    # and the down-sampled solve still lands near the full-data solution
    model_ds, _ = coord.update(np.zeros(data.num_samples))
    model_full, _ = full.update(np.zeros(data.num_samples))
    cos = (model_ds.coefficients.means @ model_full.coefficients.means) / (
        np.linalg.norm(model_ds.coefficients.means)
        * np.linalg.norm(model_full.coefficients.means))
    assert cos > 0.95


def test_fused_sweep_matches_host_descent(rng):
    """FusedSweep (one jitted scan program) must reproduce the host-paced
    CoordinateDescent trajectory: same residual semantics, same warm starts
    across outer iterations, same final model."""
    from photon_ml_tpu.game.fused import FusedSweep

    data, _, _, _ = _glmix_data(rng, n_users=12, per_user=50)
    cfg = _configs(num_iters=3)
    coords = {cid: build_coordinate(cid, data, c, cfg.task)
              for cid, c in cfg.coordinates.items()}

    host_model, _, _ = CoordinateDescent(coords, num_iterations=3).run()
    fused_model, fused_scores = FusedSweep(coords, num_iterations=3).run()

    wf_h = host_model["fixed"].coefficients.means
    wf_f = fused_model["fixed"].coefficients.means
    np.testing.assert_allclose(wf_f, wf_h, rtol=2e-3, atol=2e-3)

    re_h, re_f = host_model["per-user"], fused_model["per-user"]
    assert re_h.slot_of == re_f.slot_of
    np.testing.assert_allclose(re_f.w_stack, re_h.w_stack, rtol=2e-3, atol=2e-3)

    # fused final scores equal the model's own re-scoring
    np.testing.assert_allclose(
        fused_scores["fixed"], np.asarray(coords["fixed"].score(fused_model["fixed"])),
        rtol=1e-5, atol=1e-5)


def test_fused_sweep_warm_start(rng):
    """initial= warm start feeds both coordinate types."""
    from photon_ml_tpu.game.fused import FusedSweep

    data, _, _, _ = _glmix_data(rng, n_users=8, per_user=40)
    cfg = _configs(num_iters=2)
    coords = {cid: build_coordinate(cid, data, c, cfg.task)
              for cid, c in cfg.coordinates.items()}
    sweep = FusedSweep(coords, num_iterations=2)
    m1, _ = sweep.run()
    # warm-started fused run must track the warm-started host descent
    m2, _ = sweep.run(initial=m1)
    h2, _, _ = CoordinateDescent(coords, num_iterations=2).run(initial=m1)
    np.testing.assert_allclose(m2["fixed"].coefficients.means,
                               h2["fixed"].coefficients.means,
                               rtol=2e-3, atol=2e-3)
    np.testing.assert_allclose(m2["per-user"].w_stack,
                               h2["per-user"].w_stack, rtol=2e-3, atol=2e-3)


@pytest.mark.parametrize("projector,extra", [
    ("INDEX_MAP", {}),
    ("RANDOM", {"projected_dim": 2}),
])
def test_fused_sweep_projected_space_matches_host(rng, projector, extra):
    """Projected random effects run INSIDE the fused sweep: each bucket
    solves in its compact space and trace_publish back-projects (traced twin
    of ProjectedBuckets.back_project) — published models must match the
    host-paced loop for both projector flavors."""
    import dataclasses

    from photon_ml_tpu.types import ProjectorType

    data, _, _, _ = _glmix_data(rng, n_users=6, per_user=40)
    base = _configs(num_iters=2)
    cfg = dataclasses.replace(base, coordinates={
        "fixed": base.coordinates["fixed"],
        "per-user": dataclasses.replace(base.coordinates["per-user"],
                                        projector=ProjectorType[projector],
                                        **extra)})
    f = GameEstimator(fused=True).fit(data, [cfg])[0].model
    h = GameEstimator(fused=False).fit(data, [cfg])[0].model
    assert f["per-user"].w_stack.shape == h["per-user"].w_stack.shape
    np.testing.assert_allclose(f["fixed"].coefficients.means,
                               h["fixed"].coefficients.means,
                               rtol=2e-3, atol=2e-3)
    np.testing.assert_allclose(f["per-user"].w_stack, h["per-user"].w_stack,
                               rtol=2e-3, atol=2e-3)


def test_variance_computation_game_path(rng, tmp_path):
    """Coefficient variances through the GAME coordinate path (reference
    DistributedOptimizationProblem.scala:84-108): SIMPLE = 1/diag(H),
    FULL = diag(H^-1); persisted via BayesianLinearModelAvro.variances."""
    import dataclasses

    import scipy.special as spec

    from photon_ml_tpu.types import VarianceComputationType

    data, _, _, _ = _glmix_data(rng, n_users=6, per_user=40)
    l2 = 1.0
    base = _configs(num_iters=1)

    def closed_form_hessian(x, y_, w, off):
        z = x @ w + off
        q = spec.expit(z) * (1.0 - spec.expit(z))
        return (x * q[:, None]).T @ x + l2 * np.eye(x.shape[1])

    for kind in (VarianceComputationType.SIMPLE, VarianceComputationType.FULL):
        cfg = dataclasses.replace(base.coordinates["fixed"], variance=kind)
        coord = build_coordinate("fixed", data, cfg, base.task)
        model, res = coord.update(np.zeros(data.num_samples))
        v = model.coefficients.variances
        assert v is not None and v.shape == model.coefficients.means.shape
        x = np.asarray(data.features["global"])
        h = closed_form_hessian(x, np.asarray(data.y),
                                np.asarray(model.coefficients.means),
                                np.zeros(data.num_samples))
        expect = (1.0 / np.diag(h) if kind == VarianceComputationType.SIMPLE
                  else np.diag(np.linalg.inv(h)))
        np.testing.assert_allclose(v, expect, rtol=2e-3, atol=1e-5)

    # random effect: per-entity SIMPLE variances, entity 0 checked closed-form
    re_cfg = dataclasses.replace(base.coordinates["per-user"],
                                 variance=VarianceComputationType.SIMPLE)
    re = build_coordinate("per-user", data, re_cfg, base.task)
    re_model, _ = re.update(np.zeros(data.num_samples))
    assert re_model.variances is not None
    assert re_model.variances.shape == re_model.w_stack.shape
    eid = sorted(re_model.slot_of)[0]
    slot = re_model.slot_of[eid]
    mask = np.asarray(data.id_tags["userId"]) == eid
    xu = np.asarray(data.features["per_user"])[mask]
    h = closed_form_hessian(xu, None, re_model.w_stack[slot], np.zeros(mask.sum()))
    np.testing.assert_allclose(re_model.variances[slot], 1.0 / np.diag(h),
                               rtol=2e-3, atol=1e-5)

    # persistence roundtrip keeps variances
    from photon_ml_tpu.data.index_map import IndexMap
    from photon_ml_tpu.data.reader import EntityIndex
    from photon_ml_tpu.models.game import GameModel
    from photon_ml_tpu.storage.model_io import load_game_model, save_game_model

    imap = IndexMap.from_features([(f"f{i}", "") for i in range(xu.shape[1])],
                                  add_intercept=False)
    eidx = EntityIndex()
    for e in sorted(re_model.slot_of):
        eidx.get_or_add(str(e))
    # remap slot ids through the entity index space used at save/load
    gm = GameModel(models={"per-user": dataclasses.replace(
        re_model, slot_of={eidx.get(str(e)): s
                           for e, s in re_model.slot_of.items()})})
    out = str(tmp_path / "m")
    save_game_model(gm, out, {"per_user": imap}, {"userId": eidx},
                    base.task)
    loaded, _ = load_game_model(out, {"per_user": imap}, {"userId": eidx})
    lv = loaded["per-user"].variances
    assert lv is not None
    got = np.asarray(sorted(np.round(lv.sum(axis=1), 6)))
    want = np.asarray(sorted(np.round(re_model.variances.sum(axis=1), 6)))
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_estimator_fused_auto_matches_host(rng):
    """fused="auto" (no validation) must produce the same models as the
    host-paced loop (fused=False)."""
    data, *_ = _glmix_data(rng, n_users=8, per_user=40)
    cfg = _configs(num_iters=2)
    m_auto = GameEstimator(fused="auto").fit(data, [cfg])[0].model
    m_host = GameEstimator(fused=False).fit(data, [cfg])[0].model
    np.testing.assert_allclose(m_auto["fixed"].coefficients.means,
                               m_host["fixed"].coefficients.means,
                               rtol=2e-3, atol=2e-3)
    np.testing.assert_allclose(m_auto["per-user"].w_stack,
                               m_host["per-user"].w_stack, rtol=2e-3, atol=2e-3)

    # validation present -> the fused VALIDATED program (held-out scoring
    # in-program, suite evaluated per sweep boundary) — evaluation attached
    suite = EvaluationSuite.from_specs(["auc"])
    r = GameEstimator(validation_suite=suite, fused="auto").fit(
        data, [cfg], validation_data=data)[0]
    assert r.evaluation is not None
    r_true = GameEstimator(validation_suite=suite, fused=True).fit(
        data, [cfg], validation_data=data)[0]
    assert r_true.evaluation is not None

    # fused=True still raises on genuinely host-paced per-update work
    with pytest.raises(ValueError):
        GameEstimator(validation_suite=suite, fused=True).fit(
            data, [cfg], validation_data=data,
            checkpoint_hook=lambda m, cur, **kw: None)

    # every coordinate flavor is now fused-eligible; ineligibility is only
    # per-fit host work (checkpoint/locks/resume), asserted above


def test_reg_grid_reuses_compiled_programs(rng):
    """A reg-weight grid must re-enter the same compiled solvers/sweep:
    reg is a traced argument (reference updateRegularizationWeight:64-75
    mutates weights in place for the same reason)."""
    import dataclasses

    import jax

    data, *_ = _glmix_data(rng, n_users=6, per_user=40)
    cfg1 = _configs(num_iters=1)
    coords = {cid: build_coordinate(cid, data, c, cfg1.task)
              for cid, c in cfg1.coordinates.items()}

    # rebind with a different L2 keeps the SAME jitted callables
    f2 = coords["fixed"].rebind(dataclasses.replace(
        cfg1.coordinates["fixed"], reg=Regularization(l2=10.0)))
    assert f2._solve is coords["fixed"]._solve
    r2 = coords["per-user"].rebind(dataclasses.replace(
        cfg1.coordinates["per-user"], reg=Regularization(l2=10.0)))
    assert r2._vsolve is coords["per-user"]._vsolve
    # ...and the solutions actually differ (reg flows through the trace)
    m1, _ = coords["fixed"].update(np.zeros(data.num_samples))
    m2, _ = f2.update(np.zeros(data.num_samples))
    assert np.linalg.norm(m2.coefficients.means) < np.linalg.norm(
        m1.coefficients.means)

    # an L1-regime flip DOES rebuild (OWLQN vs L-BFGS dispatch is static)
    f3 = coords["fixed"].rebind(dataclasses.replace(
        cfg1.coordinates["fixed"], reg=Regularization(l1=0.5)))
    assert f3._solve is not coords["fixed"]._solve

    # estimator grid: one sweep program for the whole λ grid
    grid = []
    for l2 in (0.1, 1.0, 10.0):
        cs = {cid: dataclasses.replace(c, reg=Regularization(l2=l2))
              for cid, c in cfg1.coordinates.items()}
        grid.append(GameConfig(task=cfg1.task, coordinates=cs,
                               num_outer_iterations=1))
    est = GameEstimator(fused=True)
    with jax.log_compiles(False):
        results = est.fit(data, grid)
    # the three grid points must be genuinely different solutions
    w_grid = [r.model["fixed"].coefficients.means for r in results]
    assert not np.allclose(w_grid[0], w_grid[2], atol=1e-3)
    # host-paced loop agrees at each grid point
    host = GameEstimator(fused=False).fit(data, grid)
    for r, h in zip(results, host):
        np.testing.assert_allclose(r.model["fixed"].coefficients.means,
                                   h.model["fixed"].coefficients.means,
                                   rtol=2e-3, atol=2e-3)


def test_fused_grid_l1_regime_switch(rng):
    """A grid crossing the smooth/L1 boundary must NOT reuse the compiled
    sweep: the L1 point must come back sparsity-inducing and equal to the
    host loop's solution."""
    import dataclasses

    data, *_ = _glmix_data(rng, n_users=6, per_user=40)
    base = _configs(num_iters=1)
    fixed = base.coordinates["fixed"]
    grid = [
        GameConfig(task=base.task, coordinates={
            "fixed": dataclasses.replace(fixed, reg=Regularization(l2=1.0))}),
        GameConfig(task=base.task, coordinates={
            "fixed": dataclasses.replace(fixed, reg=Regularization(l1=2.0))}),
    ]
    fused = GameEstimator(fused=True).fit(data, grid)
    host = GameEstimator(fused=False).fit(data, grid)
    for f, h in zip(fused, host):
        np.testing.assert_allclose(f.model["fixed"].coefficients.means,
                                   h.model["fixed"].coefficients.means,
                                   rtol=2e-3, atol=2e-3)


def test_golden_coefficients_regression():
    """Pinned-value regression in the reference's style
    (GameEstimatorIntegTest.scala:105-107 asserts exact coefficient values
    captured from an assumed-correct run).  Guards the whole stack — data
    layout, solvers, residual descent — against silent numeric drift.
    Captured 2026-07-29 on the CPU x64 test surface, seed 20260729;
    re-captured 2026-07-30 after the batch-as-argument jit refactor (XLA
    fusion order shifted f32 rounding by ~8e-5; the f64 reference goldens
    in test_reference_golden_* pin cross-implementation correctness);
    re-captured 2026-07-31 after the approximate-Wolfe line-search slack
    (opt/linesearch.py: f32 solves now stop deterministically at the
    working-precision plateau, shifting iterates by ~2e-5 within the
    plateau-flat region);
    re-captured 2026-08-05 on the current CPU test image — the drift
    (~5e-4 relative on the per-user rows, ~4e-5 on the fixed effect) is an
    XLA-version f32 fusion-order shift, present identically at every
    repo commit back through PR 4, i.e. environmental rather than caused
    by any code change here.  The f64 reference goldens
    (test_reference_golden_*) pin cross-implementation correctness and
    were unaffected.  To regenerate after a LEGITIMATE numeric change:
    run the fit below and paste ``repr(float(x))`` of each coefficient,
    then record the cause in this docstring."""
    rng = np.random.default_rng(20260729)
    data, *_ = _glmix_data(rng, n_users=5, per_user=40)
    res = GameEstimator(fused=False).fit(data, [_configs(num_iters=2)])[0]

    golden_fixed = np.asarray([
        -0.34681177139282227, -1.5030040740966797, -0.16299287974834442,
        1.1834511756896973, 0.5667862892150879, -0.41815751791000366])
    np.testing.assert_allclose(res.model["fixed"].coefficients.means,
                               golden_fixed, rtol=1e-4, atol=1e-5)

    re_model = res.model["per-user"]
    assert sorted(re_model.slot_of) == [11, 14, 17, 20, 23]
    golden_user0 = np.asarray([
        0.7986433506011963, 0.1569463014602661, -0.6273418068885803])
    np.testing.assert_allclose(re_model.w_stack[re_model.slot_of[11]],
                               golden_user0, rtol=1e-4, atol=1e-5)


def test_per_entity_l2_multipliers(rng):
    """Per-entity regularization (beyond-reference: the reference only
    envisioned per-entity lambda, RandomEffectOptimizationProblem.scala:42):
    a heavily-multiplied entity's coefficients shrink, others are untouched;
    the fused sweep agrees with the host loop."""
    import dataclasses

    from photon_ml_tpu.game.fused import FusedSweep

    data, _, _, _ = _glmix_data(rng, n_users=8, per_user=50)
    base = _configs(num_iters=1)
    re_base = base.coordinates["per-user"]
    eids = sorted(set(int(e) for e in data.id_tags["userId"]))
    heavy = eids[2]

    def fit(cfg):
        coord = build_coordinate("u", data, cfg, base.task)
        model, _ = coord.update(np.zeros(data.num_samples))
        return coord, model

    _, plain = fit(re_base)
    cfg_mult = dataclasses.replace(
        re_base, per_entity_l2_multipliers={heavy: 1000.0})
    coord, mult = fit(cfg_mult)

    slot = plain.slot_of[heavy]
    assert (np.linalg.norm(mult.w_stack[slot])
            < 0.05 * np.linalg.norm(plain.w_stack[slot]))
    for e in eids:
        if e == heavy:
            continue
        np.testing.assert_allclose(mult.w_stack[plain.slot_of[e]],
                                   plain.w_stack[plain.slot_of[e]],
                                   rtol=1e-4, atol=1e-5)

    # config canonicalization: dict -> sorted tuple, hash/eq safe
    assert cfg_mult.per_entity_l2_multipliers == ((heavy, 1000.0),)

    # fused sweep applies the multipliers too (they're part of sweep_key)
    coords = {"u": coord}
    fused_model, _ = FusedSweep(coords, num_iterations=1).run()
    np.testing.assert_allclose(fused_model["u"].w_stack, mult.w_stack,
                               rtol=2e-3, atol=2e-3)


def test_per_entity_multipliers_cli(tmp_path):
    import json as _json
    import os

    from photon_ml_tpu.cli import train as train_cli
    from photon_ml_tpu.storage.model_io import load_game_model
    from photon_ml_tpu.data.index_map import load_index
    from photon_ml_tpu.data.reader import EntityIndex

    import sys
    tests_dir = os.path.dirname(os.path.abspath(__file__))
    if tests_dir not in sys.path:
        sys.path.insert(0, tests_dir)
    from test_cli import _write_fixture

    train_path = str(tmp_path / "train.avro")
    _write_fixture(train_path, n=300, seed=11)
    mults = str(tmp_path / "mults.json")
    with open(mults, "w") as f:
        _json.dump({"user0": 500.0, "ghost_user": 2.0}, f)

    out = str(tmp_path / "out")
    rc = train_cli.run([
        "--train-data", train_path, "--feature-shards", "all",
        "--coordinate", "name=fixed,feature.shard=all,reg.weights=1",
        "--coordinate", f"name=u,random.effect.type=userId,feature.shard=all,"
                        f"reg.weights=1,per.entity.l2.multipliers={mults}",
        "--id-tags", "userId",
        "--output-dir", out,
    ])
    assert rc == 0
    eidx = EntityIndex.load(os.path.join(out, "userId.entities.json"))
    imap = load_index(os.path.join(out, "all.idx"))
    model, _ = load_game_model(os.path.join(out, "best"), {"all": imap},
                               {"userId": eidx})
    re_model = model["u"]
    heavy_slot = re_model.slot_of[eidx.get("user0")]
    other = [s for e, s in re_model.slot_of.items()
             if e != eidx.get("user0")]
    heavy_norm = np.linalg.norm(re_model.w_stack[heavy_slot])
    other_norms = [np.linalg.norm(re_model.w_stack[s]) for s in other]
    assert heavy_norm < 0.3 * np.median(other_norms)


# --- Reference-golden parity: the reference's own pinned scikit-learn values ---

# The reference's "trivial" dataset (photon-api/src/test/.../GameTestUtils.scala:
# trivialLabeledPoints, 68-79): 10 points, 2 features; an intercept column of
# ones is appended LAST, exactly as GameEstimatorIntegTest.simpleHardcodedTest
# does before training.
_TRIVIAL_X = np.asarray([
    [-0.7306653538519616, 0.0],
    [0.6750417712898752, -0.4232874171873786],
    [0.1863463229359709, -0.8163423997075965],
    [-0.6719842051493347, 0.0],
    [0.9699938346531928, 0.0],
    [0.22759406190283604, 0.0],
    [0.9688721028330911, 0.0],
    [0.5993795346650845, 0.0],
    [0.9219423508390701, -0.8972778242305388],
    [0.7006904841584055, -0.5607635619919824],
])
_TRIVIAL_Y = np.asarray([0.0, 1.0, 1.0, 0.0, 1.0, 1.0, 1.0, 0.0, 0.0, 0.0])


def _trivial_game_data():
    x = np.concatenate([_TRIVIAL_X, np.ones((len(_TRIVIAL_Y), 1))], axis=1)
    return GameData(y=_TRIVIAL_Y, features={"features": x}, id_tags={})


def test_reference_golden_trivial_linear_l2():
    """Cross-implementation golden parity: linear regression + L2(0.3) on the
    reference's trivial dataset must reproduce the scikit-learn-derived
    coefficients the reference pins at HIGH_PRECISION_TOLERANCE
    (GameEstimatorIntegTest.scala:105-107; loss = 1/2 Σ(z-y)², reg = λ/2‖w‖²
    including the intercept)."""
    cfg = GameConfig(task=TaskType.LINEAR_REGRESSION, coordinates={
        "global": FixedEffectConfig(
            feature_shard="features",
            solver=SolverConfig(max_iters=100, tolerance=1e-11),
            reg=Regularization(l2=0.3), intercept_index=2)})
    res = GameEstimator(dtype=np.float64).fit(_trivial_game_data(), [cfg])[0]
    np.testing.assert_allclose(
        res.model["global"].coefficients.means,
        [0.3215554473500486, 0.17904355431985355, 0.4122241763914806],
        rtol=0, atol=1e-9)


@pytest.mark.parametrize("kind", ["none", "scale_with_max_magnitude",
                                  "scale_with_standard_deviation",
                                  "standardization"])
def test_reference_golden_trivial_normalization(kind):
    """GameEstimatorIntegTest.testNormalization parity: the UNregularized
    solve is invariant under every normalization type because the published
    model is mapped back to original space — all four must reproduce the
    reference's pinned scikit-learn OLS coefficients at
    LOW_PRECISION_TOLERANCE (1e-8)."""
    import jax.numpy as jnp

    from photon_ml_tpu.core.normalization import (build_normalization,
                                                  compute_feature_stats)
    from photon_ml_tpu.types import NormalizationType

    data = _trivial_game_data()
    x = data.features["features"]
    stats = compute_feature_stats(jnp.asarray(x, jnp.float64),
                                  intercept_index=2)
    ctx = build_normalization(NormalizationType(kind), stats)
    cfg = GameConfig(task=TaskType.LINEAR_REGRESSION, coordinates={
        "global": FixedEffectConfig(
            feature_shard="features",
            solver=SolverConfig(max_iters=100, tolerance=1e-11),
            reg=Regularization(), intercept_index=2)})
    res = GameEstimator(normalization={"features": ctx},
                        dtype=np.float64).fit(data, [cfg])[0]
    np.testing.assert_allclose(
        res.model["global"].coefficients.means,
        [0.34945501725815586, 0.26339479490270173, 0.4366125400310442],
        rtol=0, atol=1e-8)


def test_down_sampling_default_sampler_regression_tasks(rng):
    """Reference DownSamplerHelper.scala:33-40: regression tasks down-sample
    with DefaultDownSampler — uniform sampling at rate, NO positive-keeping
    and NO 1/rate reweighting."""
    data, *_ = _glmix_data(rng, n_users=8, per_user=40)
    cfg = FixedEffectConfig(feature_shard="global",
                            solver=SolverConfig(max_iters=20),
                            reg=Regularization(l2=1.0), down_sampling_rate=0.5)
    coord = build_coordinate("fixed", data, cfg, TaskType.LINEAR_REGRESSION)
    base = np.asarray(coord._base_weight)
    w = np.asarray(coord._down_sample_weights(seed=7))
    live = base > 0
    kept = live & (w > 0)
    # sampled rows keep their ORIGINAL weight (no compensation), others drop
    np.testing.assert_allclose(w[kept], base[kept])
    frac = kept.sum() / live.sum()
    assert 0.35 < frac < 0.65  # ~rate of the live rows survive


def test_fused_down_sampling_matches_host_statistically(rng):
    """The fused sweep now runs per-update down-sampling inside the compiled
    program (traced PRNG fold per iteration).  Draws differ from the host
    path's numpy PRNG, so parity is statistical: both must land near the
    no-sampling solution at rate→1⁻ semantics scale, and the fused solution
    must track the host solution closely on a well-conditioned problem."""
    import dataclasses

    data, *_ = _glmix_data(rng, n_users=6, per_user=80)
    base_cfg = _configs(num_iters=2)
    fixed = dataclasses.replace(base_cfg.coordinates["fixed"],
                                down_sampling_rate=0.8)
    cfg = GameConfig(task=base_cfg.task, coordinates={
        "fixed": fixed, "per-user": base_cfg.coordinates["per-user"]},
        num_outer_iterations=2)

    w_fused = GameEstimator(fused=True).fit(data, [cfg])[0] \
        .model["fixed"].coefficients.means
    w_host = GameEstimator(fused=False).fit(data, [cfg])[0] \
        .model["fixed"].coefficients.means
    # different PRNG streams -> not identical...
    assert not np.allclose(w_fused, w_host, atol=1e-12)
    # ...but the same estimator up to sampling noise
    np.testing.assert_allclose(w_fused, w_host, rtol=0.35, atol=0.15)

    # seed is a traced input: same seed reproduces, different seed varies
    coords = {cid: build_coordinate(cid, data, c, cfg.task)
              for cid, c in cfg.coordinates.items()}
    from photon_ml_tpu.game.fused import FusedSweep
    sweep = FusedSweep(coords, num_iterations=2)
    m1, _ = sweep.run(seed=3)
    m2, _ = sweep.run(seed=3)
    m3, _ = sweep.run(seed=4)
    np.testing.assert_array_equal(m1["fixed"].coefficients.means,
                                  m2["fixed"].coefficients.means)
    assert not np.array_equal(m1["fixed"].coefficients.means,
                              m3["fixed"].coefficients.means)


def test_fused_variances_match_host(rng):
    """Fused sweep computes coefficient variances in the scan body on the
    final iteration, at each coordinate's last-update offsets/weights/reg —
    must equal the host-paced path's published variances on both coordinate
    types (only the final update's variances survive there too)."""
    import dataclasses

    from photon_ml_tpu.types import VarianceComputationType

    data, *_ = _glmix_data(rng, n_users=6, per_user=40)
    base = _configs(num_iters=2)
    cfg = GameConfig(task=base.task, coordinates={
        "fixed": dataclasses.replace(base.coordinates["fixed"],
                                     variance=VarianceComputationType.SIMPLE),
        "per-user": dataclasses.replace(base.coordinates["per-user"],
                                        variance=VarianceComputationType.FULL)},
        num_outer_iterations=2)

    fused = GameEstimator(fused=True).fit(data, [cfg])[0].model
    host = GameEstimator(fused=False).fit(data, [cfg])[0].model

    fv = fused["fixed"].coefficients.variances
    hv = host["fixed"].coefficients.variances
    assert fv is not None and hv is not None
    np.testing.assert_allclose(fv, hv, rtol=1e-4, atol=1e-7)

    fr, hr = fused["per-user"], host["per-user"]
    assert fr.variances is not None and hr.variances is not None
    assert fr.slot_of == hr.slot_of
    np.testing.assert_allclose(fr.variances, hr.variances, rtol=1e-4, atol=1e-7)


def test_fused_reg_grid_variances_use_each_lambda(rng):
    """Regression: a fused λ grid reuses ONE compiled sweep whose reg enters
    as a traced argument — the published variances must be computed with EACH
    grid point's λ (not the first config's), matching the host path at every
    grid point."""
    import dataclasses

    from photon_ml_tpu.types import VarianceComputationType

    data, *_ = _glmix_data(rng, n_users=6, per_user=40)
    base = _configs(num_iters=1)
    fixed = dataclasses.replace(base.coordinates["fixed"],
                                variance=VarianceComputationType.SIMPLE)
    ruser = dataclasses.replace(base.coordinates["per-user"],
                                variance=VarianceComputationType.SIMPLE)
    grid = []
    for l2 in (0.1, 10.0):
        grid.append(GameConfig(task=base.task, coordinates={
            "fixed": dataclasses.replace(fixed, reg=Regularization(l2=l2)),
            "per-user": dataclasses.replace(ruser, reg=Regularization(l2=l2))}))

    fused = GameEstimator(fused=True).fit(data, grid)
    host = GameEstimator(fused=False).fit(data, grid)
    for f, h in zip(fused, host):
        np.testing.assert_allclose(f.model["fixed"].coefficients.variances,
                                   h.model["fixed"].coefficients.variances,
                                   rtol=1e-4, atol=1e-7)
        np.testing.assert_allclose(f.model["per-user"].variances,
                                   h.model["per-user"].variances,
                                   rtol=1e-4, atol=1e-7)
    # the two grid points' variances genuinely differ (λ enters the Hessian)
    v0 = fused[0].model["fixed"].coefficients.variances
    v1 = fused[1].model["fixed"].coefficients.variances
    assert not np.allclose(v0, v1, rtol=1e-2)


def test_storage_dtype_mixed_precision_fit(rng):
    """storage_dtype="bfloat16": design matrices live at bf16 (half the HBM
    bytes per objective pass) while solver state stays f32 — published
    coefficients must track the all-f32 fit closely on both coordinate types,
    and the fused path must accept the config."""
    import dataclasses

    data, *_ = _glmix_data(rng, n_users=6, per_user=60)
    base = _configs(num_iters=2)
    mixed = GameConfig(task=base.task, coordinates={
        "fixed": dataclasses.replace(base.coordinates["fixed"],
                                     storage_dtype="bfloat16"),
        "per-user": dataclasses.replace(base.coordinates["per-user"],
                                        storage_dtype="bfloat16")},
        num_outer_iterations=2)

    w32 = GameEstimator(fused=False).fit(data, [base])[0].model
    wbf_host = GameEstimator(fused=False).fit(data, [mixed])[0].model
    wbf_fused = GameEstimator(fused=True).fit(data, [mixed])[0].model

    for m in (wbf_host, wbf_fused):
        assert m["fixed"].coefficients.means.dtype == np.float32
        np.testing.assert_allclose(m["fixed"].coefficients.means,
                                   w32["fixed"].coefficients.means,
                                   rtol=0.08, atol=0.08)
        np.testing.assert_allclose(m["per-user"].w_stack,
                                   w32["per-user"].w_stack,
                                   rtol=0.15, atol=0.15)


def test_fused_sweep_tron_matches_host(rng):
    """TRON (trust region + truncated CG) through the fused sweep: the
    make_solver dispatch is optimizer-agnostic, so the whole-descent program
    must reproduce the host-paced TRON descent on both coordinate types."""
    import dataclasses

    from photon_ml_tpu.types import OptimizerType

    data, *_ = _glmix_data(rng, n_users=6, per_user=40)
    base = _configs(num_iters=2)
    cfg = dataclasses.replace(base, coordinates={
        "fixed": dataclasses.replace(base.coordinates["fixed"],
                                     optimizer=OptimizerType.TRON),
        "per-user": dataclasses.replace(base.coordinates["per-user"],
                                        optimizer=OptimizerType.TRON)})
    f = GameEstimator(fused=True).fit(data, [cfg])[0].model
    h = GameEstimator(fused=False).fit(data, [cfg])[0].model
    np.testing.assert_allclose(f["fixed"].coefficients.means,
                               h["fixed"].coefficients.means,
                               rtol=2e-3, atol=2e-3)
    np.testing.assert_allclose(f["per-user"].w_stack, h["per-user"].w_stack,
                               rtol=2e-3, atol=2e-3)


def test_fused_program_has_no_large_baked_constants(rng):
    """Compile-time guard: closed-over jax.Arrays lower to baked XLA
    constants and compile time grows linearly with constant bytes (118s -> 3s
    at bench scale when the design matrices moved to arguments).  The fused
    program's jaxpr consts must stay tiny — if a design matrix, score vector,
    or bucket array ever leaks back into a closure, this trips."""
    import jax

    data, *_ = _glmix_data(rng, n_users=8, per_user=50)
    cfg = _configs(num_iters=2)
    coords = {cid: build_coordinate(cid, data, c, cfg.task)
              for cid, c in cfg.coordinates.items()}
    from photon_ml_tpu.game.fused import FusedSweep

    sweep = FusedSweep(coords, num_iterations=2)
    regs = tuple(coords[cid].config.reg for cid in sweep.order)
    jaxpr = jax.make_jaxpr(sweep._program.__wrapped__)(
        *sweep._cold, sweep._vars0, regs, jax.random.PRNGKey(0),
        sweep._base, sweep._datas)
    const_bytes = sum(np.asarray(c).nbytes for c in jaxpr.consts)
    # n=400 samples: a single leaked score vector would be 3.2KB (f64) and a
    # leaked design matrix 9.6KB+ — anything over 1KB means a leak
    assert const_bytes <= 1024, f"{const_bytes} bytes of baked constants"


# --- box constraints through GAME configs (reference OptimizerConfig.scala:47,
# --- applied via OptimizationUtils.projectCoefficientsToSubspace) ---

def test_fixed_effect_constraints(rng):
    """A constrained GAME fit keeps coefficients inside bounds and matches
    scipy L-BFGS-B under the same box."""
    import scipy.optimize as sopt
    import scipy.special as sp

    n, d = 600, 6
    x = rng.normal(size=(n, d))
    w_true = rng.normal(size=d) * 2.0
    y = (rng.random(n) < 1 / (1 + np.exp(-x @ w_true))).astype(float)
    data = GameData(y=y, features={"g": x})
    l2 = 0.5
    lo, hi = -0.25, 0.25
    cfg = GameConfig(task=TaskType.LOGISTIC_REGRESSION, coordinates={
        "fixed": FixedEffectConfig(
            feature_shard="g", reg=Regularization(l2=l2),
            solver=SolverConfig(max_iters=200, tolerance=1e-9),
            constraints=tuple((j, lo, hi) for j in range(d)))})
    res = GameEstimator(dtype=np.float64).fit(data, [cfg])[0]
    w = np.asarray(res.model["fixed"].coefficients.means)
    assert np.all(w >= lo - 1e-9) and np.all(w <= hi + 1e-9)
    # some bounds must actually bind (w_true is far outside the box)
    assert np.any(np.isclose(np.abs(w), 0.25, atol=1e-6))

    def nll(wv):
        z = x @ wv
        return np.sum(np.logaddexp(0, z) - y * z) + 0.5 * l2 * wv @ wv

    def grad(wv):
        z = x @ wv
        return x.T @ (sp.expit(z) - y) + l2 * wv

    ref = sopt.minimize(nll, np.zeros(d), jac=grad, method="L-BFGS-B",
                        bounds=[(lo, hi)] * d)
    np.testing.assert_allclose(w, ref.x, atol=5e-5)


def test_random_effect_constraints(rng):
    """Constraints apply to EVERY entity's solve in the vmapped buckets."""
    n_users, per_user, d = 8, 40, 3
    n = n_users * per_user
    x = rng.normal(size=(n, d))
    uids = np.repeat(np.arange(n_users), per_user)
    wu = rng.normal(size=(n_users, d)) * 3.0
    y = (rng.random(n) < 1 / (1 + np.exp(-np.einsum(
        "nd,nd->n", x, wu[uids])))).astype(float)
    data = GameData(y=y, features={"u": x}, id_tags={"userId": uids})
    cfg = GameConfig(task=TaskType.LOGISTIC_REGRESSION, coordinates={
        "per-user": RandomEffectConfig(
            random_effect_type="userId", feature_shard="u",
            reg=Regularization(l2=0.1),
            constraints=((0, -0.5, 0.5), (2, 0.0, 1.0)))})
    res = GameEstimator().fit(data, [cfg])[0]
    m = res.model["per-user"]
    assert np.all(m.w_stack[:, 0] >= -0.5 - 1e-6)
    assert np.all(m.w_stack[:, 0] <= 0.5 + 1e-6)
    assert np.all(m.w_stack[:, 2] >= -1e-6)
    assert np.all(m.w_stack[:, 2] <= 1.0 + 1e-6)
    # feature 1 unconstrained: at least one entity escapes the [-0.5, 0.5] box
    assert np.any(np.abs(m.w_stack[:, 1]) > 0.5)


def test_constraint_validation():
    with pytest.raises(ValueError, match="lower bound"):
        FixedEffectConfig(feature_shard="g", constraints=((0, 1.0, -1.0),))
    with pytest.raises(ValueError, match="infinite"):
        FixedEffectConfig(
            feature_shard="g",
            constraints=((0, float("-inf"), float("inf")),))
    # dict form canonicalizes to sorted tuples
    c = FixedEffectConfig(feature_shard="g",
                          constraints={3: (0.0, 1.0), 1: (-1.0, 1.0)})
    assert c.constraints == ((1, -1.0, 1.0), (3, 0.0, 1.0))
    # TRON + constraints must refuse loudly at solver bind
    from photon_ml_tpu.types import OptimizerType

    data = GameData(y=np.ones(8), features={"g": np.ones((8, 2))})
    with pytest.raises(ValueError, match="box"):
        build_coordinate(
            "fixed", data,
            FixedEffectConfig(feature_shard="g", optimizer=OptimizerType.TRON,
                              constraints=((0, -1.0, 1.0),)),
            TaskType.LOGISTIC_REGRESSION)


# --- per-entity normalization for random effects (reference
# --- NormalizationContextRDD, RandomEffectOptimizationProblem.scala:154-178) ---

def _re_norm_data(rng, n_users=6, per_user=50, d=4):
    """Per-user logistic data with an intercept column and deliberately
    badly-scaled features (what normalization is for)."""
    n = n_users * per_user
    scales = np.resize(np.asarray([1.0, 0.03, 12.0, 1.0]), d)
    x = rng.normal(size=(n, d)) * scales
    x[:, 0] = 1.0  # intercept
    uids = np.repeat(np.arange(n_users), per_user)
    wu = rng.normal(size=(n_users, d))
    y = (rng.random(n) < 1 / (1 + np.exp(-np.einsum(
        "nd,nd->n", x, wu[uids])))).astype(float)
    return x, uids, y


def test_random_effect_shared_normalization_parity(rng):
    """IDENTITY projector: ONE standardization context for every entity
    (reference NormalizationContextBroadcast).  Each entity's published
    coefficients must match a direct per-entity normalized host solve."""
    import jax
    import jax.numpy as jnp

    from photon_ml_tpu.core.losses import logistic_loss
    from photon_ml_tpu.core.normalization import NormalizationContext
    from photon_ml_tpu.core.objective import GLMObjective
    from photon_ml_tpu.core.batch import dense_batch
    from photon_ml_tpu.opt.solve import make_solver

    x, uids, y = _re_norm_data(rng)
    factors = 1.0 / (np.std(x, axis=0) + 1e-12)
    shifts = np.mean(x, axis=0).copy()
    factors[0], shifts[0] = 1.0, 0.0  # intercept untouched
    norm = NormalizationContext(factors=jnp.asarray(factors, jnp.float32),
                                shifts=jnp.asarray(shifts, jnp.float32))

    data = GameData(y=y, features={"u": x}, id_tags={"userId": uids})
    cfg = RandomEffectConfig(
        random_effect_type="userId", feature_shard="u",
        reg=Regularization(l2=0.3), intercept_index=0,
        solver=SolverConfig(max_iters=100, tolerance=1e-9))
    coord = build_coordinate("u", data, cfg, TaskType.LOGISTIC_REGRESSION,
                             norm=norm)
    model, _ = coord.update(np.zeros(len(y)))

    obj = GLMObjective(loss=logistic_loss, reg=Regularization(l2=0.3), norm=norm)
    solve = jax.jit(make_solver(obj))
    for u in range(6):
        rows = uids == u
        res = solve(jnp.zeros(x.shape[1], jnp.float32),
                    dense_batch(x[rows].astype(np.float32),
                                y[rows].astype(np.float32)))
        w_ref = norm.model_to_original_space(res.w, 0)
        slot = model.slot_of[u]
        # f32 solves stop at slightly different iterates (vmapped vs single
        # reduction order); parity is semantic, not bitwise
        np.testing.assert_allclose(model.w_stack[slot], np.asarray(w_ref),
                                   rtol=1e-2, atol=1e-3)


def test_random_effect_projected_normalization_parity(rng):
    """INDEX_MAP projector: the context projected into each entity's compact
    space (reference NormalizationContextRDD case).  Compaction is exact, so
    the published model must match the IDENTITY fit with the same context."""
    import jax.numpy as jnp

    from photon_ml_tpu.core.normalization import NormalizationContext
    from photon_ml_tpu.types import ProjectorType

    x, uids, y = _re_norm_data(rng, d=5)
    # entity-disjoint sparsity so INDEX_MAP actually compacts
    for u in range(6):
        x[uids == u, 1 + (u % 3)] = 0.0
    factors = 1.0 / (np.std(x, axis=0) + 1e-12)
    factors[0] = 1.0
    norm = NormalizationContext(factors=jnp.asarray(factors, jnp.float32),
                                shifts=None)
    data = GameData(y=y, features={"u": x}, id_tags={"userId": uids})

    def fit(projector):
        cfg = RandomEffectConfig(
            random_effect_type="userId", feature_shard="u",
            reg=Regularization(l2=0.3), projector=projector,
            solver=SolverConfig(max_iters=100, tolerance=1e-9))
        coord = build_coordinate("u", data, cfg, TaskType.LOGISTIC_REGRESSION,
                                 norm=norm)
        model, _ = coord.update(np.zeros(len(y)))
        return model

    ident = fit(ProjectorType.IDENTITY)
    comp = fit(ProjectorType.INDEX_MAP)
    for u in range(6):
        np.testing.assert_allclose(comp.w_stack[comp.slot_of[u]],
                                   ident.w_stack[ident.slot_of[u]],
                                   rtol=1e-2, atol=1e-3)


def test_random_effect_standardization_under_compaction(rng):
    """STANDARDIZATION (factors + SHIFTS) under INDEX_MAP compaction: the
    context is projected per entity — factor/shift rows gathered through each
    lane's observed-column map, the margin shift folded into the lane's own
    compact intercept position (reference NormalizationContextRDD through
    IndexMapProjectorRDD.scala:34-262).  With every feature observed the
    compact solve IS the full-space solve, so INDEX_MAP must match IDENTITY
    exactly; warm-starting from the published optimum must be a fixed point
    (round-trips the per-lane modelToTransformedSpace)."""
    import jax.numpy as jnp

    from photon_ml_tpu.core.normalization import NormalizationContext
    from photon_ml_tpu.types import ProjectorType

    x, uids, y = _re_norm_data(rng, d=5)
    factors = 1.0 / (np.std(x, axis=0) + 1e-12)
    shifts = np.mean(x, axis=0).copy()
    factors[0], shifts[0] = 1.0, 0.0  # intercept untouched
    norm = NormalizationContext(factors=jnp.asarray(factors, jnp.float32),
                                shifts=jnp.asarray(shifts, jnp.float32))
    data = GameData(y=y, features={"u": x}, id_tags={"userId": uids})

    def coord(projector):
        cfg = RandomEffectConfig(
            random_effect_type="userId", feature_shard="u",
            reg=Regularization(l2=0.3), projector=projector,
            intercept_index=0,
            solver=SolverConfig(max_iters=100, tolerance=1e-9))
        return build_coordinate("u", data, cfg, TaskType.LOGISTIC_REGRESSION,
                                norm=norm)

    ci = coord(ProjectorType.IDENTITY)
    cc = coord(ProjectorType.INDEX_MAP)
    assert cc._norm_per_lane and cc._norm_shift_dev is not None
    mi, _ = ci.update(np.zeros(len(y)))
    mc, _ = cc.update(np.zeros(len(y)))
    for u in range(6):
        np.testing.assert_allclose(mc.w_stack[mc.slot_of[u]],
                                   mi.w_stack[mi.slot_of[u]],
                                   rtol=1e-2, atol=1e-3)
    # warm start from the optimum is a fixed point (inverse map round-trip)
    # up to the f32 working-precision plateau: the approximate-Wolfe slack
    # lets a re-solve wander within the plateau-flat region (~4e-3 along
    # ill-conditioned directions), so the TIGHT invariant is the training
    # objective — per-sample logistic loss of the two models' scores must
    # agree to working precision — while coefficients get plateau room
    mc2, _ = cc.update(np.zeros(len(y)), init=mc)
    np.testing.assert_allclose(mc2.w_stack, mc.w_stack, rtol=1e-2, atol=5e-3)
    s1 = np.asarray(cc.score(mc), np.float64)
    s2 = np.asarray(cc.score(mc2), np.float64)
    loss1 = float(np.mean(np.logaddexp(0, s1) - y * s1))
    loss2 = float(np.mean(np.logaddexp(0, s2) - y * s2))
    np.testing.assert_allclose(loss2, loss1, rtol=1e-5)
    # fused program publishes the same model
    state = cc.init_sweep_state()
    sdata = cc.sweep_data()
    state, _ = cc.trace_update(state, jnp.zeros(len(y), jnp.float32),
                               data=sdata)
    w_stack = np.asarray(cc.trace_publish(state, data=sdata))
    np.testing.assert_allclose(w_stack, mc.w_stack, rtol=1e-4, atol=1e-5)


def test_sparse_re_standardization_matches_densified_compaction(rng):
    """Shift normalization on a SPARSE random-effect shard (the round-3
    refusal at the old game/coordinate.py:674): row-sparse compaction with a
    per-row intercept slot must match the densified INDEX_MAP fit — the two
    compact paths project the context identically."""
    import jax.numpy as jnp

    from photon_ml_tpu.core.normalization import NormalizationContext
    from photon_ml_tpu.game.data import SparseShard
    from photon_ml_tpu.types import ProjectorType

    n_users, per_user, d, k = 8, 48, 32, 5
    n = n_users * per_user
    uids = np.repeat(np.arange(n_users), per_user)
    # k-sparse rows over features 1..d-1 plus an explicit intercept column 0
    idx = np.concatenate(
        [np.zeros((n, 1), np.int32),
         rng.integers(1, d, size=(n, k)).astype(np.int32)], axis=1)
    vals = np.concatenate(
        [np.ones((n, 1), np.float32),
         (rng.normal(size=(n, k)) * 3.0 + 1.0).astype(np.float32)], axis=1)
    wu = rng.normal(size=(n_users, d)).astype(np.float32) * 0.5
    margins = np.einsum("nk,nk->n", vals, np.take_along_axis(
        wu[uids], idx, axis=1))
    y = (rng.random(n) < 1 / (1 + np.exp(-margins))).astype(np.float32)
    dense = np.zeros((n, d), np.float32)
    np.add.at(dense, (np.repeat(np.arange(n), k + 1), idx.ravel()),
              vals.ravel())

    factors = np.ones(d, np.float32)
    factors[1:] = 0.4
    shifts = np.zeros(d, np.float32)
    shifts[1:] = 1.0  # nonzero shifts on every non-intercept feature
    norm = NormalizationContext(factors=jnp.asarray(factors),
                                shifts=jnp.asarray(shifts))

    def coord(features, projector):
        cfg = RandomEffectConfig(
            random_effect_type="userId", feature_shard="u",
            reg=Regularization(l2=0.5), projector=projector,
            intercept_index=0,
            solver=SolverConfig(max_iters=60, tolerance=1e-9))
        gd = GameData(y=y, features={"u": features}, id_tags={"userId": uids})
        return build_coordinate("u", gd, cfg, TaskType.LOGISTIC_REGRESSION,
                                norm=norm)

    cs = coord(SparseShard(indices=idx, values=vals, dim=d),
               ProjectorType.IDENTITY)
    cd = coord(dense, ProjectorType.INDEX_MAP)
    ms, _ = cs.update(np.zeros(n))
    md, _ = cd.update(np.zeros(n))
    assert ms.w_stack.shape == md.w_stack.shape == (n_users, d)
    for u in range(n_users):
        np.testing.assert_allclose(ms.w_stack[ms.slot_of[u]],
                                   md.w_stack[md.slot_of[u]],
                                   rtol=1e-2, atol=1e-3)


def test_random_effect_normalization_rejections(rng):
    import jax.numpy as jnp

    from photon_ml_tpu.core.normalization import NormalizationContext
    from photon_ml_tpu.types import ProjectorType

    x, uids, y = _re_norm_data(rng)
    data = GameData(y=y, features={"u": x}, id_tags={"userId": uids})
    norm_shift = NormalizationContext(factors=None,
                                      shifts=jnp.asarray(np.full(4, 0.5)))
    # INDEX_MAP + shifts is SUPPORTED (round 4: per-lane projected contexts)
    # but needs intercept_index so each lane's compact intercept position can
    # absorb the margin shift
    with pytest.raises(ValueError, match="intercept_index"):
        build_coordinate(
            "u", data,
            RandomEffectConfig(random_effect_type="userId", feature_shard="u",
                               projector=ProjectorType.INDEX_MAP),
            TaskType.LOGISTIC_REGRESSION, norm=norm_shift)
    shift0 = np.full(4, 0.5)
    shift0[0] = 0.0  # the intercept column itself is never shifted
    coord_im = build_coordinate(
        "u", data,
        RandomEffectConfig(random_effect_type="userId", feature_shard="u",
                           projector=ProjectorType.INDEX_MAP,
                           intercept_index=0),
        TaskType.LOGISTIC_REGRESSION,
        norm=NormalizationContext(factors=None, shifts=jnp.asarray(shift0)))
    assert coord_im._norm_shift_dev is not None
    # factor normalization under RANDOM projection is SUPPORTED (round 3):
    # the context is pushed through the Gaussian matrix and shared
    # (ProjectionMatrixBroadcast.projectNormalizationContext; full parity
    # coverage in tests/test_projection.py) — only shift normalization
    # WITHOUT an intercept_index still refuses
    coord = build_coordinate(
        "u", data,
        RandomEffectConfig(random_effect_type="userId", feature_shard="u",
                           projector=ProjectorType.RANDOM, projected_dim=2),
        TaskType.LOGISTIC_REGRESSION,
        norm=NormalizationContext(factors=jnp.ones(4) * 2.0, shifts=None))
    assert coord._norm_proj is not None
    with pytest.raises(ValueError, match="intercept_index"):
        build_coordinate(
            "u", data,
            RandomEffectConfig(random_effect_type="userId", feature_shard="u",
                               projector=ProjectorType.RANDOM, projected_dim=2),
            TaskType.LOGISTIC_REGRESSION, norm=norm_shift)


def test_lower_bound_existing_model_semantics(rng):
    """Reference RandomEffectDataset.scala:322-333 + RandomEffectCoordinate
    .updateModel:114-127: with a warm-start model, an under-bound entity
    ALREADY covered by it is not retrained (its model passes through
    unchanged), while an under-bound NEW entity still trains; without a
    warm start, under-bound entities are dropped outright."""
    from photon_ml_tpu.models.game import RandomEffectModel

    d = 4
    # entity 0: 16 samples; entity 1: 2 samples (under bound), IN the prior;
    # entity 2: 2 samples (under bound), NOT in the prior
    uids = np.concatenate([np.zeros(16), np.ones(2), np.full(2, 2)]).astype(np.int64)
    n = len(uids)
    x = rng.normal(size=(n, d)).astype(np.float32)
    y = (rng.random(n) > 0.5).astype(np.float32)
    data = GameData(y=y, features={"u": x}, id_tags={"userId": uids})
    cfg = RandomEffectConfig(random_effect_type="userId", feature_shard="u",
                             solver=SolverConfig(max_iters=20),
                             reg=Regularization(l2=1.0),
                             min_active_samples=4)
    prior_w = np.asarray([[1.0, 2.0, 3.0, 4.0], [5.0, 6.0, 7.0, 8.0]],
                         np.float32)
    prior = RandomEffectModel(w_stack=prior_w, slot_of={0: 0, 1: 1},
                              random_effect_type="userId", feature_shard="u",
                              task=TaskType.LOGISTIC_REGRESSION)

    # no warm start: under-bound entities dropped outright
    cold = build_coordinate("u", data, cfg, TaskType.LOGISTIC_REGRESSION)
    m_cold, _ = cold.update(np.zeros(n, np.float32))
    assert set(m_cold.slot_of) == {0}

    # warm start: entity 1 (under-bound, prior) NOT retrained — its prior
    # coefficients pass through; entity 2 (under-bound, new) IS trained
    warm = build_coordinate("u", data, cfg, TaskType.LOGISTIC_REGRESSION,
                            existing_model_keys=frozenset(prior.slot_of))
    assert set(warm.buckets.lane_of) == {0, 2}
    m_warm, _ = warm.update(np.zeros(n, np.float32), init=prior)
    assert set(m_warm.slot_of) == {0, 1, 2}
    np.testing.assert_array_equal(
        m_warm.w_stack[m_warm.slot_of[1]], prior_w[1])
    # retrained entities moved off the prior
    assert np.max(np.abs(m_warm.w_stack[m_warm.slot_of[0]] - prior_w[0])) > 1e-3
    # the carried entity's samples score with its carried model
    sc = warm.score(m_warm)
    expected = x[16:18] @ prior_w[1]
    np.testing.assert_allclose(sc[16:18], expected, rtol=1e-5)

    # estimator path (fused): same semantics end-to-end
    est = GameEstimator()
    config = GameConfig(task=TaskType.LOGISTIC_REGRESSION,
                        coordinates={"user": cfg})
    res = est.fit(data, [config],
                  initial_model=GameModel(models={"user": prior}), seed=0)[0]
    m_fused = res.model["user"]
    assert set(m_fused.slot_of) == {0, 1, 2}
    np.testing.assert_array_equal(
        m_fused.w_stack[m_fused.slot_of[1]], prior_w[1])


def test_warm_start_carry_through_fused_matches_host(rng):
    """Carried entities' samples contribute a CONSTANT score to every
    residual; the fused program folds it into the base offsets, so a
    2-coordinate warm-started fused fit must match the host loop (which
    re-scores the merged model each update) — and both must differ from a
    fit that ignores the carried prior."""
    d_g, d_u = 5, 3
    uids = np.concatenate([np.zeros(24), np.ones(2), np.full(24, 2)]).astype(np.int64)
    n = len(uids)
    xg = rng.normal(size=(n, d_g)).astype(np.float32)
    xu = rng.normal(size=(n, d_u)).astype(np.float32)
    y = (rng.random(n) > 0.5).astype(np.float32)
    data = GameData(y=y, features={"g": xg, "u": xu}, id_tags={"userId": uids})
    solver = SolverConfig(max_iters=30, tolerance=1e-8)
    config = GameConfig(
        task=TaskType.LOGISTIC_REGRESSION, num_outer_iterations=2,
        coordinates={
            "fixed": FixedEffectConfig(feature_shard="g", solver=solver,
                                       reg=Regularization(l2=1.0)),
            "user": RandomEffectConfig(random_effect_type="userId",
                                       feature_shard="u", solver=solver,
                                       reg=Regularization(l2=1.0),
                                       min_active_samples=4)})
    from photon_ml_tpu.models.game import RandomEffectModel

    prior_w = (rng.normal(size=(1, d_u)) * 2.0).astype(np.float32)
    prior = GameModel(models={"user": RandomEffectModel(
        w_stack=prior_w, slot_of={1: 0}, random_effect_type="userId",
        feature_shard="u", task=TaskType.LOGISTIC_REGRESSION)})

    m_fused = GameEstimator().fit(data, [config], initial_model=prior,
                                  seed=0)[0].model
    m_host = GameEstimator(fused=False).fit(data, [config],
                                            initial_model=prior,
                                            seed=0)[0].model
    # entity 1 (under-bound, in prior): carried identically by both paths
    for m in (m_fused, m_host):
        np.testing.assert_array_equal(
            m["user"].w_stack[m["user"].slot_of[1]], prior_w[0])
    # the FIXED coordinate saw the carried residual identically
    np.testing.assert_allclose(m_fused["fixed"].coefficients.means,
                               m_host["fixed"].coefficients.means, atol=2e-4)
    np.testing.assert_allclose(
        m_fused["user"].w_stack[m_fused["user"].slot_of[0]],
        m_host["user"].w_stack[m_host["user"].slot_of[0]], atol=2e-4)
    # and the carried prior is load-bearing: without it the fixed effect
    # trains against a different residual
    m_cold = GameEstimator().fit(data, [config], seed=0)[0].model
    assert np.max(np.abs(m_cold["fixed"].coefficients.means
                         - m_fused["fixed"].coefficients.means)) > 1e-3


def test_compact_random_effect_model(rng):
    """CompactRandomEffectModel (wide-vocabulary published container):
    round-trips with the dense stack, scores identically on BOTH shard
    kinds including missing entities, and its memory is O(entities x
    observed) rather than O(entities x vocabulary)."""
    from photon_ml_tpu.game.data import SparseShard
    from photon_ml_tpu.models.game import RandomEffectModel

    e, d, k_obs = 24, 512, 6
    w = np.zeros((e, d), np.float32)
    for i in range(e):
        cols = rng.choice(d, size=k_obs, replace=False)
        w[i, cols] = rng.normal(size=k_obs)
    w[3] = 0.0  # an all-zero entity must survive the round trip
    slot_of = {100 + i * 7: i for i in range(e)}
    dense = RandomEffectModel(w_stack=w, slot_of=slot_of,
                              random_effect_type="userId", feature_shard="u")
    compact = dense.to_compact()
    # memory claim + exact round trip
    assert compact.values.nbytes + compact.indices.nbytes < w.nbytes / 10
    np.testing.assert_array_equal(compact.to_dense().w_stack, w)
    assert compact.to_dense().slot_of == slot_of

    # scoring parity, dense shard (+ unknown entity ids -> 0)
    n = 200
    uids = rng.choice(list(slot_of) + [999999], size=n)
    x = rng.normal(size=(n, d)).astype(np.float32)
    data_dense = GameData(y=np.zeros(n), features={"u": x},
                          id_tags={"userId": uids})
    s_dense = np.asarray(dense.score(data_dense))
    s_compact = np.asarray(compact.score(data_dense))
    np.testing.assert_allclose(s_compact, s_dense, rtol=1e-6, atol=1e-6)
    assert np.all(s_compact[uids == 999999] == 0.0)

    # scoring parity, sparse shard (feature ids hit AND miss the model's
    # observed columns)
    ks = 5
    f_idx = rng.integers(0, d, size=(n, ks)).astype(np.int32)
    f_val = rng.normal(size=(n, ks)).astype(np.float32)
    data_sparse = GameData(
        y=np.zeros(n),
        features={"u": SparseShard(indices=f_idx, values=f_val, dim=d)},
        id_tags={"userId": uids})
    np.testing.assert_allclose(np.asarray(compact.score(data_sparse)),
                               np.asarray(dense.score(data_sparse)),
                               rtol=1e-6, atol=1e-6)

    # capacity guard: k below the densest entity refuses loudly
    with pytest.raises(ValueError, match="capacity"):
        dense.to_compact(k=k_obs - 1)
    # variance-carrying models refuse (variances' support differs)
    import dataclasses as _dc
    with pytest.raises(ValueError, match="variances"):
        _dc.replace(dense, variances=np.ones_like(w)).to_compact()
    # explicit roomier capacity still round-trips
    np.testing.assert_array_equal(dense.to_compact(k=k_obs + 3)
                                  .to_dense().w_stack, w)


def test_constraint_space_transformed_reference_compat(rng):
    """The reference applies constraintMap bounds RAW to the transformed-
    space iterate every TRON/LBFGS iteration (TRON.scala:228 ->
    OptimizationUtils.projectCoefficientsToSubspace, OptimizationUtils
    .scala:56-58) — even under normalization that rescales and shifts, so
    the PUBLISHED original-space coefficients can violate the written
    bounds.  constraint_space="transformed" reproduces that faithfully;
    this test pins BOTH the reference's numbers (scipy bounded solve on
    the transformed design) and the deviation the default space refuses
    to produce."""
    import scipy.optimize as sopt
    import scipy.special as sp

    import jax.numpy as jnp

    from photon_ml_tpu.core.normalization import NormalizationContext

    n, d = 800, 3
    x = np.empty((n, d))
    x[:, 0] = 1.0                               # intercept
    x[:, 1] = rng.normal(size=n) * 0.1 + 0.5    # tiny scale, shifted
    x[:, 2] = rng.normal(size=n)
    w_true = np.asarray([0.2, 8.0, -1.0])
    y = (rng.random(n) < 1 / (1 + np.exp(-x @ w_true))).astype(float)
    data = GameData(y=y, features={"g": x})
    l2 = 0.5
    bounds = (1, -0.3, 0.3)  # binds hard: unconstrained w_t[1] ~ 0.8

    mean = x.mean(axis=0)
    std = x.std(axis=0)
    factors = 1.0 / np.where(std == 0, 1.0, std)
    shifts = mean.copy()
    factors[0], shifts[0] = 1.0, 0.0            # intercept untouched
    norm = NormalizationContext(factors=jnp.asarray(factors),
                                shifts=jnp.asarray(shifts))

    def fit(space):
        cfg = GameConfig(task=TaskType.LOGISTIC_REGRESSION, coordinates={
            "fixed": FixedEffectConfig(
                feature_shard="g", reg=Regularization(l2=l2),
                solver=SolverConfig(max_iters=300, tolerance=1e-10),
                intercept_index=0, constraints=(bounds,),
                constraint_space=space)})
        est = GameEstimator(dtype=np.float64, normalization={"g": norm})
        return est.fit(data, [cfg])[0]

    # default space: honest refusal (the repo's documented deviation)
    with pytest.raises(ValueError, match="non-separable under shifts"):
        fit("original")

    res = fit("transformed")
    w_orig = np.asarray(res.model["fixed"].coefficients.means)
    # published ORIGINAL-space coefficient violates the written bound —
    # exactly what the reference ships (the questionable half of faithful)
    assert abs(w_orig[1]) > 0.3 + 0.5

    # pin the reference's numbers: bounded scipy solve on the TRANSFORMED
    # design (x_t = (x - mean) * factors) with raw bounds
    xt = (x - shifts) * factors

    def nll(wv):
        z = xt @ wv
        return np.sum(np.logaddexp(0, z) - y * z) + 0.5 * l2 * wv @ wv

    def grad(wv):
        z = xt @ wv
        return xt.T @ (sp.expit(z) - y) + l2 * wv

    ref = sopt.minimize(nll, np.zeros(d), jac=grad, method="L-BFGS-B",
                        bounds=[(None, None), (-0.3, 0.3), (None, None)])
    # map the repo's published model back to transformed space and compare
    w_t = np.asarray(norm.model_to_transformed_space(jnp.asarray(w_orig), 0))
    np.testing.assert_allclose(w_t, ref.x, atol=5e-5)
    assert abs(w_t[1]) <= 0.3 + 1e-9  # raw bound respected where applied


def test_constraint_space_validation():
    with pytest.raises(ValueError, match="constraint_space"):
        FixedEffectConfig(feature_shard="g", constraint_space="bogus")
    from photon_ml_tpu.cli.config_grammar import parse_coordinate_spec

    spec = parse_coordinate_spec(
        "name=f,feature.shard=g,constraint.space=transformed,reg.weights=1")
    assert spec.template.constraint_space == "transformed"


def test_constraint_space_transformed_compact_refusal(rng):
    """transformed + compact (sparse/INDEX_MAP) + normalization must refuse
    loudly: the per-lane compact solve applies bounds with ORIGINAL
    semantics, so silently accepting the compat flag would produce exactly
    the reference divergence it exists to prevent (MIGRATION.md)."""
    from photon_ml_tpu.core.normalization import NormalizationContext
    from photon_ml_tpu.types import ProjectorType

    n_users, per_user, d = 4, 12, 3
    n = n_users * per_user
    x = rng.normal(size=(n, d))
    uids = np.repeat(np.arange(n_users), per_user)
    y = (rng.random(n) < 0.5).astype(float)
    data = GameData(y=y, features={"u": x}, id_tags={"userId": uids})
    import jax.numpy as jnp
    norm = NormalizationContext(factors=jnp.ones(d) * 2.0, shifts=None)
    cfg = RandomEffectConfig(
        random_effect_type="userId", feature_shard="u",
        projector=ProjectorType.INDEX_MAP,
        constraints=((0, -0.5, 0.5),), constraint_space="transformed")
    with pytest.raises(ValueError, match="transformed.*compact|compact.*transformed"):
        build_coordinate("u", data, cfg, TaskType.LOGISTIC_REGRESSION,
                         norm=norm)
