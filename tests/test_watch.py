"""photonwatch tests (photon_ml_tpu/obs/watch/*, the federation surfaces
on the metrics endpoint, the admission fleet-pressure latch, and the
fleetwatch CLI).

The contracts under test (ISSUE 20):
  - DeltaExporter: frame 1 is the full registry, later frames carry only
    changed series; histogram change detection keys on (count, total).
  - FleetView: counters summed across processes, gauges kept per process
    under an added ``process=`` label, histograms bucket-merged on a
    shared ladder and degraded to per-process series on a mismatch;
    delta-stream sequence gaps drop the frame and mark the source for
    resync; staleness reported per source.
  - SLOEngine: multi-window burn-rate math for availability (counter
    quotient) and latency (histogram ladder above-threshold) objectives,
    cold-start burns are 0.0, alert latch edges (firing then resolved,
    exactly once each), ``fleet_slo_burn_rate`` gauges published, the
    firing edge dumps the flight recorder.
  - attribution: device/host split accumulates ``xla_*_seconds{site=}``
    and stamps ``device_us``/``host_us`` onto the enclosing span; the
    disabled path hands back a shared no-op.
  - ``GET /watchz`` always-full pull and ``GET /fleetz`` on a
    FleetView-wired endpoint (404 without one).
  - AdmissionController ``fleet_burn_budget``: shed with reason
    ``fleet_pressure`` while the published burn gauge is over budget,
    hysteresis release at the resume watermark.
  - ``export_build_info``: ``photon_build_info{version=,role=}`` and
    ``process_start_time_seconds`` in every process registry.
  - tools/fleetwatch.py: ``poll_once`` over live HTTP, ``--once`` snapshot
    to stdout with exit status tied to peer reachability.
"""

import json
import os
import socket
import sys
import time

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from photon_ml_tpu.obs import pulse
from photon_ml_tpu.obs.registry import (MetricsRegistry, export_build_info,
                                        process_start_time)
from photon_ml_tpu.obs.trace import Tracer, set_tracer, get_tracer
from photon_ml_tpu.obs.watch import (SLO, DeltaExporter, FleetView,
                                     SLOEngine, SLOEvalThread, attribute,
                                     attribution_enabled,
                                     disable_attribution,
                                     enable_attribution, load_slos)
from photon_ml_tpu.obs.watch.attribution import _NOOP
from photon_ml_tpu.serving.frontend.admission import (SHED_FLEET,
                                                      AdmissionConfig,
                                                      AdmissionController)
from photon_ml_tpu.serving.frontend.metrics_http import \
    ThreadedMetricsEndpoint
from photon_ml_tpu.serving.metrics import ServingMetrics


def _http_get(port, path):
    with socket.create_connection(("127.0.0.1", port), timeout=10) as s:
        s.sendall(f"GET {path} HTTP/1.0\r\n\r\n".encode())
        data = b""
        while True:
            chunk = s.recv(65536)
            if not chunk:
                break
            data += chunk
    status = int(data.split(b" ", 2)[1])
    return status, data.split(b"\r\n\r\n", 1)[1]


# ---------------------------------------------------------------------------
# federation: DeltaExporter
# ---------------------------------------------------------------------------
class TestDeltaExporter:
    def test_first_frame_is_full(self):
        reg = MetricsRegistry()
        reg.inc("a_total", 3)
        reg.set_gauge("depth", 7, queue="q0")
        reg.observe("lat_s", 0.01)
        exp = DeltaExporter(reg, label="p0")
        f = exp.frame()
        assert f["full"] and f["seq"] == 1 and f["label"] == "p0"
        assert [c[0] for c in f["counters"]] == ["a_total"]
        assert f["counters"][0][2] == 3
        assert f["gauges"][0][:2] == ["depth", [["queue", "q0"]]]
        assert f["histograms"][0][0] == "lat_s"
        assert f["histograms"][0][2]["count"] == 1

    def test_delta_frames_carry_only_changes(self):
        reg = MetricsRegistry()
        reg.inc("a_total")
        reg.inc("b_total")
        reg.observe("lat_s", 0.01)
        exp = DeltaExporter(reg)
        exp.frame()
        # nothing moved: empty delta
        f2 = exp.frame()
        assert not f2["full"] and f2["seq"] == 2
        assert f2["counters"] == [] and f2["histograms"] == []
        # one counter and the histogram move; b_total stays out
        reg.inc("a_total")
        reg.observe("lat_s", 0.02)
        f3 = exp.frame()
        assert [c[0] for c in f3["counters"]] == ["a_total"]
        assert f3["counters"][0][2] == 2
        assert [h[0] for h in f3["histograms"]] == ["lat_s"]
        assert f3["histograms"][0][2]["count"] == 2


# ---------------------------------------------------------------------------
# federation: FleetView merge semantics
# ---------------------------------------------------------------------------
class TestFleetView:
    def _frame(self, reg, label):
        return DeltaExporter(reg, label=label).frame()

    def test_counters_sum_across_processes(self):
        a, b = MetricsRegistry(), MetricsRegistry()
        a.inc("req_total", 2)
        b.inc("req_total", 5)
        view = FleetView()
        assert view.ingest("a", self._frame(a, "a"))
        assert view.ingest("b", self._frame(b, "b"))
        assert sum(view.registry.counter_series("req_total").values()) == 7

    def test_gauges_keep_process_identity(self):
        a, b = MetricsRegistry(), MetricsRegistry()
        a.set_gauge("queue_depth", 3)
        b.set_gauge("queue_depth", 11)
        view = FleetView()
        view.ingest("a", self._frame(a, "a"))
        view.ingest("b", self._frame(b, "b"))
        series = view.registry.gauge_series("queue_depth")
        by_proc = {dict(lk)["process"]: v for lk, v in series.items()}
        assert by_proc == {"a": 3, "b": 11}

    def test_histograms_bucket_merge_on_shared_ladder(self):
        a, b = MetricsRegistry(), MetricsRegistry()
        a.observe("lat_s", 0.001)
        a.observe("lat_s", 0.002)
        b.observe("lat_s", 0.004)
        view = FleetView()
        view.ingest("a", self._frame(a, "a"))
        view.ingest("b", self._frame(b, "b"))
        states = view.registry.histogram_state_series("lat_s")
        assert len(states) == 1
        st = next(iter(states.values()))
        assert st["count"] == 3
        assert st["total"] == pytest.approx(0.007)

    def test_ladder_mismatch_degrades_to_per_process(self):
        a = MetricsRegistry()
        a.observe("lat_s", 0.001)
        fa = self._frame(a, "a")
        # hand-craft a peer whose ladder disagrees: merge must NOT guess
        fb = json.loads(json.dumps(fa))
        fb["label"] = "b"
        fb["histograms"][0][2]["bounds"] = \
            [x * 2 for x in fb["histograms"][0][2]["bounds"]]
        view = FleetView()
        view.ingest("a", fa)
        view.ingest("b", fb)
        states = view.registry.histogram_state_series("lat_s")
        assert len(states) == 2
        procs = {dict(lk)["process"] for lk in states}
        assert procs == {"a", "b"}

    def test_seq_gap_drops_frame_and_marks_resync(self):
        reg = MetricsRegistry()
        reg.inc("a_total")
        exp = DeltaExporter(reg, label="p")
        view = FleetView()
        assert view.ingest("p", exp.frame())       # seq 1 (full)
        reg.inc("a_total")
        exp.frame()                                # seq 2 lost in transit
        reg.inc("a_total")
        f3 = exp.frame()                           # seq 3 arrives
        assert view.ingest("p", f3) is False
        snap = view.fleet_snapshot()
        assert snap["sources"]["p"]["resyncs"] == 1
        # merged view still holds the pre-gap value, not a hole
        assert sum(view.registry.counter_series("a_total").values()) == 1

    def test_staleness_reported_per_source(self):
        reg = MetricsRegistry()
        reg.inc("a_total")
        view = FleetView(stale_after_s=0.05)
        view.ingest("fresh", self._frame(reg, "fresh"))
        frame = self._frame(reg, "old")
        frame["at_unix"] = time.time() - 10.0
        view.ingest("old", frame)
        snap = view.fleet_snapshot()
        assert snap["sources"]["old"]["stale"] is True
        assert snap["sources"]["fresh"]["stale"] is False

    def test_watchz_full_pull_is_ingestible(self):
        m = ServingMetrics()
        m.registry.inc("front_requests_total", 4)
        state = m.watch_state()
        assert state["full"] is True
        view = FleetView()
        assert view.ingest("p", state)
        assert sum(view.registry.counter_series(
            "front_requests_total").values()) == 4


# ---------------------------------------------------------------------------
# SLO engine
# ---------------------------------------------------------------------------
def _avail_slo(**kw):
    base = dict(name="avail", objective=0.99, kind="availability",
                total="req_total", bad=("shed_total",),
                fast=(5.0, 20.0), slow=(10.0, 40.0),
                fast_burn=2.0, slow_burn=1.5)
    base.update(kw)
    return SLO(**base)


class TestSLOEngine:
    def test_cold_start_burns_zero(self):
        reg = MetricsRegistry()
        reg.inc("req_total", 100)
        eng = SLOEngine([_avail_slo()])
        assert eng.evaluate(reg, now=100.0) == []
        gauges = eng._publish or reg
        burn = reg.gauge_series("fleet_slo_burn_rate")
        assert list(burn.values()) == [0.0]

    def test_availability_fire_and_resolve_edges(self):
        reg = MetricsRegistry()
        eng = SLOEngine([_avail_slo()])
        now = 100.0
        # healthy traffic long enough to anchor every window
        for _ in range(50):
            reg.inc("req_total", 10)
            eng.evaluate(reg, now=now)
            now += 1.0
        assert eng.events() == []
        # burn: half the traffic shed -> ratio 0.5, burn 50 over every
        # window once the short anchors land
        for _ in range(30):
            reg.inc("req_total", 10)
            reg.inc("shed_total", 5)
            eng.evaluate(reg, now=now)
            now += 1.0
        assert eng.firing() == ["avail"]
        # heal: clean traffic until every window drains
        for _ in range(50):
            reg.inc("req_total", 10)
            eng.evaluate(reg, now=now)
            now += 1.0
        assert eng.firing() == []
        states = [(e["slo"], e["state"]) for e in eng.events()]
        assert states == [("avail", "firing"), ("avail", "resolved")]

    def test_latency_counts_above_threshold_from_ladder(self):
        reg = MetricsRegistry()
        slo = SLO(name="lat", objective=0.9, kind="latency",
                  histogram="lat_s", threshold_s=0.016,
                  fast=(5.0, 20.0), slow=(10.0, 40.0),
                  fast_burn=2.0, slow_burn=1.5)
        eng = SLOEngine([slo])
        now = 100.0
        for _ in range(30):
            reg.observe("lat_s", 0.002)
            eng.evaluate(reg, now=now)
            now += 1.0
        assert eng.events() == []
        for _ in range(30):
            reg.observe("lat_s", 0.05)       # above threshold: bad
            eng.evaluate(reg, now=now)
            now += 1.0
        assert eng.firing() == ["lat"]

    def test_publishes_burn_gauges_into_publish_registry(self):
        source, target = MetricsRegistry(), MetricsRegistry()
        eng = SLOEngine([_avail_slo()], publish=target)
        eng.evaluate(source, now=100.0)
        assert dict(target.gauge_series("fleet_slo_burn_rate"))
        assert source.gauge_series("fleet_slo_burn_rate") == {}

    def test_duplicate_slo_names_rejected(self):
        with pytest.raises(ValueError):
            SLOEngine([_avail_slo(), _avail_slo()])

    def test_firing_edge_dumps_flight_recorder(self, tmp_path):
        prev = pulse.set_flight(pulse.FlightRecorder(str(tmp_path)))
        try:
            reg = MetricsRegistry()
            eng = SLOEngine([_avail_slo()])
            now = 100.0
            for _ in range(50):
                reg.inc("req_total", 10)
                eng.evaluate(reg, now=now)
                now += 1.0
            for _ in range(30):
                reg.inc("req_total", 10)
                reg.inc("shed_total", 8)
                eng.evaluate(reg, now=now)
                now += 1.0
            assert eng.firing() == ["avail"]
            recorder = pulse.get_flight()
            assert any("slo_burn" in d["reason"]
                       for d in recorder.index())
        finally:
            pulse.set_flight(prev)

    def test_on_alert_callback_sees_both_edges(self):
        seen = []
        reg = MetricsRegistry()
        eng = SLOEngine([_avail_slo()], on_alert=seen.append)
        now = 100.0
        for _ in range(50):
            reg.inc("req_total", 10)
            eng.evaluate(reg, now=now)
            now += 1.0
        for _ in range(30):
            reg.inc("req_total", 10)
            reg.inc("shed_total", 8)
            eng.evaluate(reg, now=now)
            now += 1.0
        for _ in range(60):
            reg.inc("req_total", 10)
            eng.evaluate(reg, now=now)
            now += 1.0
        assert [e["state"] for e in seen] == ["firing", "resolved"]

    def test_load_slos_roundtrip(self, tmp_path):
        spec = [{"name": "a", "objective": 0.99, "kind": "availability",
                 "bad": ["shed_total"], "fast": [1.0, 4.0],
                 "slow": [2.0, 8.0]}]
        p = tmp_path / "slos.json"
        p.write_text(json.dumps(spec))
        slos = load_slos(str(p))
        assert len(slos) == 1 and slos[0].name == "a"
        assert slos[0].fast == (1.0, 4.0)

    def test_eval_thread_ticks_engine(self):
        reg = MetricsRegistry()
        reg.inc("req_total")
        eng = SLOEngine([_avail_slo()])
        thread = SLOEvalThread(eng, lambda: reg, interval_s=0.01).start()
        try:
            deadline = time.monotonic() + 5.0
            while not eng._tracks[0].samples:
                assert time.monotonic() < deadline, "eval thread never ran"
                time.sleep(0.01)
        finally:
            thread.stop()


# ---------------------------------------------------------------------------
# attribution
# ---------------------------------------------------------------------------
class TestAttribution:
    def teardown_method(self):
        disable_attribution()

    def test_disabled_returns_shared_noop(self):
        disable_attribution()
        assert not attribution_enabled()
        assert attribute("serve.execute") is _NOOP
        with attribute("serve.execute"):
            pass  # no registry, no tracer touched

    def test_split_accumulates_site_gauges(self):
        reg = MetricsRegistry()
        enable_attribution(reg)
        with attribute("serve.execute"):
            time.sleep(0.002)
        with attribute("serve.execute"):
            pass
        dev = reg.gauge_series("xla_device_seconds")
        host = reg.gauge_series("xla_host_seconds")
        assert {dict(lk)["site"] for lk in dev} == {"serve.execute"}
        assert {dict(lk)["site"] for lk in host} == {"serve.execute"}
        assert list(host.values())[0] >= 0.002

    def test_stamps_split_onto_enclosing_span(self):
        reg = MetricsRegistry()
        enable_attribution(reg)
        prev = set_tracer(Tracer(capacity=64, enabled=True))
        try:
            tracer = get_tracer()
            with tracer.span("serve.execute", bucket=8) as sp:
                with attribute("serve.execute", sp):
                    pass
            events = tracer.chrome_trace()["traceEvents"]
            ev = [e for e in events if e["name"] == "serve.execute"][-1]
            assert "device_us" in ev["args"] and "host_us" in ev["args"]
        finally:
            set_tracer(prev)


# ---------------------------------------------------------------------------
# build-info contract
# ---------------------------------------------------------------------------
class TestBuildInfo:
    def test_every_process_exports_identity(self):
        reg = MetricsRegistry()
        export_build_info(reg, role="replica")
        info = reg.gauge_series("photon_build_info")
        assert len(info) == 1
        labels = dict(next(iter(info)))
        assert labels["role"] == "replica" and labels["version"]
        assert list(info.values()) == [1]
        start = reg.gauge_series("process_start_time_seconds")
        assert list(start.values()) == [pytest.approx(
            process_start_time())]


# ---------------------------------------------------------------------------
# HTTP surfaces + admission consult + fleetwatch CLI
# ---------------------------------------------------------------------------
class TestWatchHTTP:
    def test_watchz_serves_ingestible_full_state(self):
        m = ServingMetrics()
        m.registry.inc("front_requests_total", 3)
        ep = ThreadedMetricsEndpoint(m, port=0).start()
        try:
            status, body = _http_get(ep.port, "/watchz")
            assert status == 200
            frame = json.loads(body)
            assert frame["full"] is True
            view = FleetView()
            assert view.ingest("p", frame)
            assert sum(view.registry.counter_series(
                "front_requests_total").values()) == 3
        finally:
            ep.stop()

    def test_fleetz_requires_a_fleet_view(self):
        m = ServingMetrics()
        ep = ThreadedMetricsEndpoint(m, port=0).start()
        try:
            status, _ = _http_get(ep.port, "/fleetz")
            assert status == 404
        finally:
            ep.stop()

    def test_fleetz_serves_fleet_snapshot(self):
        src = MetricsRegistry()
        src.inc("req_total", 2)
        view = FleetView()
        view.ingest("p", DeltaExporter(src, label="p").frame())
        ep = ThreadedMetricsEndpoint(ServingMetrics(registry=view.registry),
                                     port=0, fleet_view=view).start()
        try:
            status, body = _http_get(ep.port, "/fleetz")
            assert status == 200
            snap = json.loads(body)
            assert snap["processes"] == 1
            assert "p" in snap["sources"]
        finally:
            ep.stop()


class TestAdmissionFleetPressure:
    def test_shed_and_hysteresis_release(self):
        reg = MetricsRegistry()
        reg.set_gauge("fleet_slo_burn_rate", 10.0, slo="lat")
        adm = AdmissionController(
            AdmissionConfig(budget_s=5.0, fleet_burn_budget=1.0,
                            fleet_burn_poll_s=0.01),
            registry=reg)
        v = adm.decide(0.0)
        assert not v.admitted and v.reason == SHED_FLEET
        assert v.retry_after_ms > 0
        # over the resume watermark: latch holds
        reg.set_gauge("fleet_slo_burn_rate", 0.9, slo="lat")
        time.sleep(0.02)
        assert not adm.decide(0.0).admitted
        # under it: release
        reg.set_gauge("fleet_slo_burn_rate", 0.1, slo="lat")
        time.sleep(0.02)
        assert adm.decide(0.0).admitted

    def test_off_by_default(self):
        reg = MetricsRegistry()
        reg.set_gauge("fleet_slo_burn_rate", 99.0, slo="lat")
        adm = AdmissionController(AdmissionConfig(budget_s=5.0),
                                  registry=reg)
        assert adm.decide(0.0).admitted


class TestFleetwatchCLI:
    def _endpoint(self, counter_value=5):
        m = ServingMetrics()
        m.registry.inc("front_requests_total", counter_value)
        return ThreadedMetricsEndpoint(m, port=0).start()

    def test_poll_once_merges_live_peers(self):
        from tools.fleetwatch import poll_once
        ep = self._endpoint()
        try:
            view = FleetView()
            ok = poll_once(view, [("front", "127.0.0.1", ep.port)])
            assert ok == 1
            assert sum(view.registry.counter_series(
                "front_requests_total").values()) == 5
        finally:
            ep.stop()

    def test_once_mode_writes_snapshot_and_exit_status(self, tmp_path):
        from tools.fleetwatch import run
        ep = self._endpoint()
        out = tmp_path / "snap.json"
        try:
            rc = run([f"front=127.0.0.1:{ep.port}", "--once",
                      "--out", str(out)])
        finally:
            ep.stop()
        assert rc == 0
        snap = json.loads(out.read_text())
        assert snap["processes"] == 1
        # every peer down -> nonzero exit, snapshot still written
        rc = run([f"front=127.0.0.1:{ep.port}", "--once", "--timeout",
                  "0.2", "--out", str(out)])
        assert rc == 1

    def test_peer_spec_validation(self):
        from tools.fleetwatch import run
        assert run(["not-a-peer", "--once"]) == 2
