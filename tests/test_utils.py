"""Utils tests: PhotonLogger, Timed, EventEmitter, linalg helpers."""

import logging
import os

import numpy as np
import pytest

from photon_ml_tpu.utils import (Event, EventEmitter, EventListener,
                                 PhotonLogger, Timed, cholesky_inverse, timed)
from photon_ml_tpu.utils.linalg import solve_psd


class TestLogging:
    def test_photon_logger_writes_file(self, tmp_path):
        path = str(tmp_path / "out" / "log-message.txt")
        with PhotonLogger(path, name="test.photon") as log:
            log.info("phase %s done", "train")
            log.logger.handlers[0].flush()
        with open(path) as f:
            assert "phase train done" in f.read()

    def test_timed_sink(self):
        seen = {}
        with Timed("phase", sink=lambda label, s: seen.update({label: s})):
            pass
        assert "phase" in seen and seen["phase"] >= 0

    def test_timed_decorator(self):
        @timed("work")
        def f(x):
            return x + 1

        assert f(1) == 2


class TestEvents:
    def test_emit_and_listen(self):
        emitter = EventEmitter()
        got = []
        emitter.register(lambda e: got.append(e))
        ev = emitter.emit("training_start", task="logistic")
        assert got == [ev]
        assert got[0].payload["task"] == "logistic"

    def test_register_by_name(self):
        emitter = EventEmitter()
        listener = emitter.register(
            "photon_ml_tpu.utils.events:EventListener")
        assert isinstance(listener, EventListener)
        emitter.close_listeners()


class TestLinalg:
    def test_cholesky_inverse(self, rng):
        a = rng.normal(size=(6, 6))
        spd = a @ a.T + 6 * np.eye(6)
        inv = np.asarray(cholesky_inverse(spd))
        np.testing.assert_allclose(inv, np.linalg.inv(spd), atol=1e-8)

    def test_solve_psd(self, rng):
        a = rng.normal(size=(5, 5))
        spd = a @ a.T + 5 * np.eye(5)
        b = rng.normal(size=5)
        x = np.asarray(solve_psd(spd, b))
        np.testing.assert_allclose(spd @ x, b, atol=1e-8)

    def test_jitter(self):
        near_singular = np.zeros((3, 3))
        inv = np.asarray(cholesky_inverse(near_singular, jitter=1.0))
        np.testing.assert_allclose(inv, np.eye(3), atol=1e-10)


# ---------------------------------------------------------------------------
# Date ranges (reference DateRange.scala / DaysRange.scala / IOUtils:113-153)
# ---------------------------------------------------------------------------

def test_date_range_parsing():
    import datetime

    import pytest

    from photon_ml_tpu.utils.dates import DateRange, DaysRange, resolve_range

    r = DateRange.from_string("20170101-20170105")
    assert r.start == datetime.date(2017, 1, 1)
    assert r.end == datetime.date(2017, 1, 5)
    assert len(r.days()) == 5
    assert str(r) == "20170101-20170105"

    with pytest.raises(ValueError):
        DateRange.from_string("20170105-20170101")  # start after end
    with pytest.raises(ValueError):
        DateRange.from_string("2017-01-01")  # wrong grammar

    d = DaysRange.from_string("90-1")
    today = datetime.date(2017, 4, 11)
    dr = d.to_date_range(today)
    assert dr.start == today - datetime.timedelta(days=90)
    assert dr.end == today - datetime.timedelta(days=1)
    with pytest.raises(ValueError):
        DaysRange.from_string("1-90")  # start must be further back

    with pytest.raises(ValueError):
        resolve_range("20170101-20170105", "90-1")  # mutually exclusive
    assert resolve_range(None, None) is None


def test_input_paths_within_date_range(tmp_path):
    import pytest

    from photon_ml_tpu.utils.dates import DateRange, input_paths_within_date_range

    base = tmp_path / "daily"
    for day in ("2017/01/01", "2017/01/02", "2017/01/04"):
        (base / day).mkdir(parents=True)

    r = DateRange.from_string("20170101-20170105")
    paths = input_paths_within_date_range([str(base)], r)
    assert [p[-10:] for p in paths] == ["2017/01/01", "2017/01/02", "2017/01/04"]

    with pytest.raises(FileNotFoundError):  # Jan 3 missing
        input_paths_within_date_range([str(base)], r, error_on_missing=True)
    with pytest.raises(FileNotFoundError):  # no day at all in range
        input_paths_within_date_range([str(base)], DateRange.from_string(
            "20180101-20180102"))


def test_compilation_cache_setup(tmp_path, monkeypatch):
    from photon_ml_tpu.utils.compile_cache import enable_compilation_cache

    d = str(tmp_path / "cache")
    assert enable_compilation_cache(d) == d
    import os

    assert os.path.isdir(d)
    monkeypatch.setenv("PHOTON_COMPILE_CACHE", "0")
    assert enable_compilation_cache() is None
    monkeypatch.setenv("PHOTON_COMPILE_CACHE", str(tmp_path / "env"))
    assert enable_compilation_cache() == str(tmp_path / "env")


def test_sparse_feature_stats_match_dense():
    """compute_feature_stats_sparse == compute_feature_stats on the densified
    twin (unique indices per row — duplicates are documented-approximate)."""
    import numpy as np

    from photon_ml_tpu.core.normalization import (compute_feature_stats,
                                                  compute_feature_stats_sparse)

    rng = np.random.default_rng(0)
    n, d, k = 500, 40, 6
    idx = np.stack([rng.choice(d - 1, size=k, replace=False)
                    for _ in range(n)]).astype(np.int32)
    vals = rng.normal(size=(n, k)).astype(np.float32)
    vals[rng.random((n, k)) < 0.2] = 0.0  # padded slots
    # column d-1 observed (nonzero, strictly positive) in EVERY row: its
    # min/max must be the true extremes, not the implicit-zero default
    idx[:, -1] = d - 1
    vals[:, -1] = rng.random(n).astype(np.float32) + 0.5
    w = rng.random(n).astype(np.float32) + 0.5
    dense = np.zeros((n, d), np.float32)
    np.add.at(dense, (np.repeat(np.arange(n), k), idx.ravel()), vals.ravel())
    sd = compute_feature_stats(np.asarray(dense), np.asarray(w), intercept_index=3)
    ss = compute_feature_stats_sparse(idx, vals, d, weight=w, intercept_index=3)
    for f in ("mean", "variance", "abs_max", "num_nonzeros", "min", "max",
              "count"):
        np.testing.assert_allclose(np.asarray(getattr(sd, f)),
                                   np.asarray(getattr(ss, f)),
                                   atol=1e-4, rtol=1e-3, err_msg=f)


class TestChunkedDevicePut:
    """Bounded-RPC host->device transfer (utils/transfer.py): byte-identical
    to a direct jnp.asarray, whatever the chunk/threshold geometry."""

    def test_matches_direct_path(self, monkeypatch):
        import numpy as np

        from photon_ml_tpu.utils.transfer import chunked_device_put

        rng = np.random.default_rng(0)
        a = rng.normal(size=(1000, 7)).astype(np.float32)
        # force chunking: 1KB threshold, 4KB chunks -> ~36 slices
        monkeypatch.setenv("PHOTON_CHUNKED_PUT_MIN_MB", str(1 / 1024))
        out = chunked_device_put(a, chunk_bytes=4096)
        np.testing.assert_array_equal(np.asarray(out), a)
        # dtype narrowing happens host-side before transfer
        out16 = chunked_device_put(a, "bfloat16", chunk_bytes=4096)
        assert str(out16.dtype) == "bfloat16"

    def test_chunks_along_largest_axis(self, monkeypatch):
        """A transposed narrow array ([d, n] — score_samples_t layout) has a
        tiny leading axis; chunking must slice the LARGEST axis or the
        upload degenerates to the one giant RPC the helper exists to
        prevent."""
        import numpy as np

        from photon_ml_tpu.utils import transfer

        monkeypatch.setenv("PHOTON_CHUNKED_PUT_MIN_MB", str(1 / 1024))
        calls = []
        real = transfer.jnp.asarray

        def counting(a, *args, **kw):
            calls.append(np.shape(a))
            return real(a, *args, **kw)

        monkeypatch.setattr(transfer, "jnp",
                            type("J", (), {"asarray": staticmethod(counting),
                                           "zeros": transfer.jnp.zeros}))
        a = np.arange(2 * 5000, dtype=np.float32).reshape(2, 5000)
        out = np.asarray(transfer.chunked_device_put(a.T.copy().T,
                                                     chunk_bytes=4096))
        np.testing.assert_array_equal(out, a)
        assert len(calls) > 1 and all(s[0] == 2 for s in calls)

    def test_small_and_disabled_take_direct_path(self, monkeypatch):
        """Byte-identity can't distinguish the paths, so count the transfer
        calls: the direct path is exactly ONE jnp.asarray of the whole
        array — a regression that chunks small/disabled inputs fails here."""
        import numpy as np

        from photon_ml_tpu.utils import transfer

        calls = []
        real = transfer.jnp.asarray

        def counting(a, *args, **kw):
            calls.append(np.shape(a))
            return real(a, *args, **kw)

        monkeypatch.setattr(transfer, "jnp",
                            type("J", (), {"asarray": staticmethod(counting),
                                           "zeros": transfer.jnp.zeros}))
        a = np.arange(12, dtype=np.float32).reshape(3, 4)
        np.testing.assert_array_equal(
            np.asarray(transfer.chunked_device_put(a, chunk_bytes=8)), a)
        assert calls == [(3, 4)]  # small: one whole-array transfer
        calls.clear()
        monkeypatch.setenv("PHOTON_CHUNKED_PUT_MIN_MB", "0")
        big = np.zeros((1000, 7), np.float32)
        np.testing.assert_array_equal(
            np.asarray(transfer.chunked_device_put(big, chunk_bytes=8)), big)
        assert calls == [(1000, 7)]  # disabled: one whole-array transfer
