"""Projector tests (reference analog: IndexMapProjectorRDDIntegTest,
ProjectionMatrixTest, LocalDataset Pearson-filter tests — SURVEY.md §4)."""

import numpy as np
import pytest

from photon_ml_tpu.core.regularization import Regularization
from photon_ml_tpu.game.config import RandomEffectConfig
from photon_ml_tpu.game.coordinate import RandomEffectCoordinate
from photon_ml_tpu.game.data import GameData
from photon_ml_tpu.opt.types import SolverConfig
from photon_ml_tpu.parallel.bucketing import bucket_by_entity
from photon_ml_tpu.parallel.projection import (
    build_observed_indices,
    build_random_projection,
    pearson_scores,
    project_buckets,
)
from photon_ml_tpu.types import ProjectorType, TaskType


def _sparse_entity_data(rng, n_entities=12, per_entity=20, d=32):
    """Each entity observes only a small random subset of features."""
    n = n_entities * per_entity
    eids = np.repeat(np.arange(n_entities), per_entity).astype(np.int64)
    x = np.zeros((n, d), np.float32)
    for e in range(n_entities):
        cols = rng.choice(d - 1, size=5, replace=False)  # leave col d-1 = intercept
        rows = slice(e * per_entity, (e + 1) * per_entity)
        x[rows, cols] = rng.normal(size=(per_entity, 5)).astype(np.float32)
    x[:, d - 1] = 1.0  # intercept column observed everywhere
    w = rng.normal(size=d).astype(np.float32)
    y = (rng.random(n) < 1.0 / (1.0 + np.exp(-(x @ w)))).astype(np.float32)
    return eids, x, y


def test_pearson_scores_match_numpy(rng):
    n, d = 200, 6
    x = rng.normal(size=(n, d))
    y = x[:, 0] * 2.0 + rng.normal(size=n) * 0.1
    w = np.ones(n)
    got = pearson_scores(x, y, w)
    for j in range(d):
        expect = abs(np.corrcoef(x[:, j], y)[0, 1])
        assert got[j] == pytest.approx(expect, abs=1e-6)
    # Constant columns carry no per-entity signal and score 0; the intercept's
    # survival is the caller's intercept_index pin (build_observed_indices),
    # so an entity-constant attribute feature can't hijack the carve-out.
    xc = np.concatenate([x, np.ones((n, 1)), np.full((n, 1), 2.0)], axis=1)
    s = pearson_scores(xc, y, w)
    assert s[-2] == 0.0 and s[-1] == 0.0


def test_observed_projection_margin_exact(rng):
    eids, x, y = _sparse_entity_data(rng)
    buckets = bucket_by_entity(eids, x, y)
    assert len(buckets.buckets) == 1
    b = buckets.buckets[0]
    proj = build_observed_indices(b, buckets.dim)
    assert proj.d_proj < buckets.dim  # actually compacted
    xp = proj.project_x(b.x)
    w_proj = rng.normal(size=(b.num_lanes, proj.d_proj)).astype(np.float32)
    w_full = proj.back_project(w_proj)
    # margins identical in both spaces for every lane/sample
    m_proj = np.einsum("esd,ed->es", xp, w_proj)
    m_full = np.einsum("esd,ed->es", b.x, w_full)
    np.testing.assert_allclose(m_proj, m_full, rtol=1e-5, atol=1e-5)


def test_random_projection_margin_exact(rng):
    d, dp = 32, 8
    proj = build_random_projection(d, dp, seed=3)
    x = rng.normal(size=(4, 10, d)).astype(np.float32)
    xp = proj.project_x(x)
    w_proj = rng.normal(size=(4, dp)).astype(np.float32)
    w_full = proj.back_project(w_proj)
    np.testing.assert_allclose(
        np.einsum("esd,ed->es", xp, w_proj),
        np.einsum("esd,ed->es", x, w_full), rtol=1e-4, atol=1e-4)


def test_pearson_ratio_caps_features_and_keeps_intercept(rng):
    eids, x, y = _sparse_entity_data(rng, per_entity=16)
    buckets = bucket_by_entity(eids, x, y)
    b = buckets.buckets[0]
    d = buckets.dim
    proj = build_observed_indices(b, d, features_to_samples_ratio=0.25,
                                  intercept_index=d - 1)
    for lane in range(b.num_lanes):
        k = int(b.counts[lane])
        kept = proj.indices[lane][proj.indices[lane] >= 0]
        assert len(kept) <= max(1, int(np.ceil(0.25 * k)))
        assert (d - 1) in kept  # intercept survives the cut


def test_re_coordinate_index_map_matches_identity(rng):
    eids, x, y = _sparse_entity_data(rng)
    data = GameData(y=y, features={"s": x}, id_tags={"e": eids})
    solver = SolverConfig(max_iters=60, tolerance=1e-9)
    kw = dict(random_effect_type="e", feature_shard="s", solver=solver,
              reg=Regularization(l2=0.5))
    base = RandomEffectCoordinate(
        "re", data, RandomEffectConfig(**kw), TaskType.LOGISTIC_REGRESSION)
    projected = RandomEffectCoordinate(
        "re", data, RandomEffectConfig(projector=ProjectorType.INDEX_MAP, **kw),
        TaskType.LOGISTIC_REGRESSION)
    offs = np.zeros(len(y), np.float32)
    m0, _ = base.update(offs)
    m1, _ = projected.update(offs)
    # zero-init + L2 ==> unobserved coords stay 0; optima coincide
    np.testing.assert_allclose(np.asarray(m1.w_stack), np.asarray(m0.w_stack),
                               rtol=1e-3, atol=2e-3)
    np.testing.assert_allclose(projected.score(m1), base.score(m0),
                               rtol=1e-3, atol=2e-3)
    # warm start from the projected model converges immediately to itself
    m2, _ = projected.update(offs, init=m1)
    np.testing.assert_allclose(np.asarray(m2.w_stack), np.asarray(m1.w_stack),
                               rtol=1e-3, atol=2e-3)


def test_re_coordinate_random_projection_runs(rng):
    eids, x, y = _sparse_entity_data(rng)
    data = GameData(y=y, features={"s": x}, id_tags={"e": eids})
    coord = RandomEffectCoordinate(
        "re", data,
        RandomEffectConfig(random_effect_type="e", feature_shard="s",
                           solver=SolverConfig(max_iters=20),
                           reg=Regularization(l2=0.5),
                           projector=ProjectorType.RANDOM, projected_dim=8),
        TaskType.LOGISTIC_REGRESSION)
    model, _ = coord.update(np.zeros(len(y), np.float32))
    assert np.asarray(model.w_stack).shape[1] == x.shape[1]  # full-dim model
    assert np.all(np.isfinite(np.asarray(model.w_stack)))
    scores = coord.score(model)
    assert np.all(np.isfinite(scores))


def test_project_buckets_requires_dim_for_random(rng):
    eids, x, y = _sparse_entity_data(rng, n_entities=3, per_entity=4)
    buckets = bucket_by_entity(eids, x, y)
    with pytest.raises(ValueError):
        project_buckets(buckets, ProjectorType.RANDOM)
    with pytest.raises(ValueError):
        project_buckets(buckets, ProjectorType.IDENTITY)
    # Pearson/intercept knobs are INDEX_MAP-only: rejected, not ignored
    with pytest.raises(ValueError, match="INDEX_MAP"):
        project_buckets(buckets, ProjectorType.RANDOM, projected_dim=4,
                        features_to_samples_ratio=0.5)


def test_random_projection_normalization_parity():
    """Normalization under RANDOM projection: the coordinate context is
    pushed through the Gaussian matrix and shared by every entity
    (reference ProjectionMatrixBroadcast.projectNormalizationContext:102-112,
    intercept pass-through ProjectionMatrix.scala:112-120).  Must equal the
    reference-order manual computation: project design + context by hand,
    solve per-entity in the projected space (IDENTITY path), back-project."""
    import jax.numpy as jnp

    from photon_ml_tpu.core.normalization import NormalizationContext
    from photon_ml_tpu.core.regularization import Regularization
    from photon_ml_tpu.game import GameData
    from photon_ml_tpu.game.config import RandomEffectConfig
    from photon_ml_tpu.game.coordinate import build_coordinate
    from photon_ml_tpu.opt.types import SolverConfig
    from photon_ml_tpu.parallel.projection import build_random_projection
    from photon_ml_tpu.types import ProjectorType, TaskType

    rng = np.random.default_rng(9)
    n, d, n_users, d_proj = 512, 48, 8, 12
    x = rng.normal(size=(n, d)).astype(np.float32) * np.linspace(
        0.5, 3.0, d).astype(np.float32)
    x[:, -1] = 1.0  # intercept column
    uids = np.repeat(np.arange(n_users), n // n_users)
    rng.shuffle(uids)
    wu = (rng.normal(size=(n_users, d)) * 0.4).astype(np.float32)
    margins = np.einsum("nd,nd->n", x, wu[uids])
    y = (rng.random(n) < 1 / (1 + np.exp(-margins))).astype(np.float32)

    fac = (1.0 / np.maximum(x.std(axis=0), 1e-6)).astype(np.float32)
    fac[-1] = 1.0
    shifts = x.mean(axis=0).astype(np.float32)
    shifts[-1] = 0.0
    norm = NormalizationContext(factors=fac, shifts=shifts)

    solver = SolverConfig(max_iters=40, tolerance=1e-8)
    cfg = RandomEffectConfig(random_effect_type="userId", feature_shard="u",
                             solver=solver, reg=Regularization(l2=1.0),
                             projector=ProjectorType.RANDOM,
                             projected_dim=d_proj, intercept_index=d - 1)
    gd = GameData(y=y, features={"u": x}, id_tags={"userId": uids})
    c = build_coordinate("u", gd, cfg, TaskType.LOGISTIC_REGRESSION,
                         norm=norm, seed=3)
    m, _ = c.update(np.zeros(n, np.float32))

    rp = build_random_projection(d, d_proj, seed=3, dtype=np.float32,
                                 intercept_index=d - 1)
    ctx, p_ii = rp.project_normalization(norm)
    x_p = rp.project_x(x)
    np.testing.assert_allclose(x_p[:, -1], x[:, -1])  # intercept exact
    cfg_id = RandomEffectConfig(random_effect_type="userId",
                                feature_shard="u", solver=solver,
                                reg=Regularization(l2=1.0),
                                intercept_index=p_ii)
    gd_p = GameData(y=y, features={"u": x_p}, id_tags={"userId": uids})
    c2 = build_coordinate("u", gd_p, cfg_id, TaskType.LOGISTIC_REGRESSION,
                          norm=NormalizationContext(factors=ctx.factors,
                                                    shifts=ctx.shifts),
                          seed=3)
    m2, _ = c2.update(np.zeros(n, np.float32))
    w_manual = rp.back_project(m2.w_stack)
    np.testing.assert_allclose(m.w_stack, w_manual, atol=1e-4)

    # the context is load-bearing: dropping it changes the solution
    c_raw = build_coordinate("u", gd, cfg, TaskType.LOGISTIC_REGRESSION,
                             seed=3)
    m_raw, _ = c_raw.update(np.zeros(n, np.float32))
    assert np.max(np.abs(m_raw.w_stack - m.w_stack)) > 1e-3

    # fused sweep path publishes the same model (trace_publish order:
    # transformed->original projected space, then back-projection)
    state = c.init_sweep_state()
    state, _score = c.trace_update(state, jnp.zeros(n, jnp.float32))
    w_fused = np.asarray(c.trace_publish(state))
    np.testing.assert_allclose(w_fused, m.w_stack, atol=1e-4)


def test_random_projection_shift_requires_intercept():
    from photon_ml_tpu.core.normalization import NormalizationContext
    from photon_ml_tpu.core.regularization import Regularization
    from photon_ml_tpu.game import GameData
    from photon_ml_tpu.game.config import RandomEffectConfig
    from photon_ml_tpu.game.coordinate import build_coordinate
    from photon_ml_tpu.opt.types import SolverConfig
    from photon_ml_tpu.types import ProjectorType, TaskType

    rng = np.random.default_rng(0)
    x = rng.normal(size=(64, 8)).astype(np.float32)
    uids = np.repeat(np.arange(4), 16)
    y = (rng.random(64) < 0.5).astype(np.float32)
    norm = NormalizationContext(factors=None,
                                shifts=x.mean(axis=0).astype(np.float32))
    cfg = RandomEffectConfig(random_effect_type="userId", feature_shard="u",
                             solver=SolverConfig(max_iters=5),
                             reg=Regularization(l2=1.0),
                             projector=ProjectorType.RANDOM, projected_dim=4)
    gd = GameData(y=y, features={"u": x}, id_tags={"userId": uids})
    with pytest.raises(ValueError, match="intercept_index"):
        build_coordinate("u", gd, cfg, TaskType.LOGISTIC_REGRESSION,
                         norm=norm)


def test_index_map_simple_variances_match_identity():
    """SIMPLE variances under INDEX_MAP compaction equal the IDENTITY
    computation: diag(H) is per-feature and margin-invariant; unobserved
    features carry prior-only 1/λ2."""
    from photon_ml_tpu.core.regularization import Regularization
    from photon_ml_tpu.game import GameData
    from photon_ml_tpu.game.config import RandomEffectConfig
    from photon_ml_tpu.game.coordinate import build_coordinate
    from photon_ml_tpu.opt.types import SolverConfig
    from photon_ml_tpu.types import (ProjectorType, TaskType,
                                     VarianceComputationType)

    rng = np.random.default_rng(4)
    n, d, n_users = 256, 24, 8
    x = rng.normal(size=(n, d)).astype(np.float32)
    # per-entity sparsity so INDEX_MAP actually compacts: zero half the
    # columns per user
    uids = np.repeat(np.arange(n_users), n // n_users)
    mask = np.ones((n, d), bool)
    for u in range(n_users):
        cols = rng.choice(d, size=d // 2, replace=False)
        mask[np.ix_(uids == u, cols)] = False
    x = np.where(mask, x, 0.0).astype(np.float32)
    y = (rng.random(n) > 0.5).astype(np.float32)
    gd = GameData(y=y, features={"u": x}, id_tags={"userId": uids})
    l2 = 3.0

    def fit(projector):
        cfg = RandomEffectConfig(random_effect_type="userId",
                                 feature_shard="u",
                                 solver=SolverConfig(max_iters=25),
                                 reg=Regularization(l2=l2),
                                 projector=projector,
                                 variance=VarianceComputationType.SIMPLE)
        c = build_coordinate("u", gd, cfg, TaskType.LOGISTIC_REGRESSION)
        m, _ = c.update(np.zeros(n, np.float32))
        return m

    m_id = fit(ProjectorType.IDENTITY)
    m_im = fit(ProjectorType.INDEX_MAP)
    np.testing.assert_allclose(m_im.w_stack, m_id.w_stack, atol=5e-4)
    np.testing.assert_allclose(m_im.variances, m_id.variances, rtol=2e-3)


def test_index_map_soa_newton_matches_vmapped(rng, monkeypatch):
    """Narrow INDEX_MAP-projected buckets gate onto the SoA Newton solver
    (the gate keys on projected solve-space shapes); the published
    full-dim model matches the generic vmapped path."""
    eids, x, y = _sparse_entity_data(rng)
    data = GameData(y=y, features={"s": x}, id_tags={"e": eids})
    kw = dict(random_effect_type="e", feature_shard="s",
              solver=SolverConfig(max_iters=60, tolerance=1e-9),
              reg=Regularization(l2=0.5), projector=ProjectorType.INDEX_MAP)
    cs = RandomEffectCoordinate("re", data, RandomEffectConfig(**kw),
                                TaskType.LOGISTIC_REGRESSION)
    if not cs._use_soa:
        pytest.skip("fixture shapes exceed the SoA gate: "
                    + str([b.x.shape for b in cs._proj.buckets]))
    offs = np.zeros(len(y), np.float32)
    ms, _ = cs.update(offs)

    monkeypatch.setenv("PHOTON_DISABLE_SOA_NEWTON", "1")
    cv = RandomEffectCoordinate("re", data, RandomEffectConfig(**kw),
                                TaskType.LOGISTIC_REGRESSION)
    assert not cv._use_soa
    mv, _ = cv.update(offs)
    np.testing.assert_allclose(np.asarray(ms.w_stack),
                               np.asarray(mv.w_stack), rtol=1e-3, atol=2e-3)
