"""photonrepl tests: delta-log shipping over the network (ISSUE 13).

The contracts under test:
  - Wire: record lines round-trip bit-identically to the on-disk log
    frame; a tampered CRC or malformed frame is a typed WireError, never
    a silent corruption of the mirror.
  - Snapshot: model-dir tar packing is deterministic (byte-identical for
    an unchanged dir), CRC-checked, and unpacking refuses traversal and
    non-file members.
  - Bootstrap: a replica with an empty spool snapshots the owner's base
    over the socket and converges BITWISE to the owner's live scores
    with zero engine recompiles after warm.
  - Resume: a reconnecting replica with a warm spool resumes via log
    replay (``repl_resume_total{mode="log"}``); one whose identity was
    compacted past falls back to a fresh snapshot.
  - In-stream hot swap: an owner swap ships the new base inline; the
    replica hot-swaps with replay-before-activate off its mirror and
    stays bitwise-converged.
  - Retention: a connected follower's acknowledged identity pins the
    owner's compaction floor; byte/age caps evict abusive pinners to
    snapshot-bootstrap instead of letting them pin the log forever.
  - Auth: both the replication socket and the serving front end refuse a
    missing/wrong shared secret with exactly one error frame.
  - Chaos (the regression ISSUE 13 names): torn log tail + owner restart
    + compaction + follower resume still lands the replica on the
    owner's identity chain, bitwise-converged.
"""

import json
import os
import socket
import time

import numpy as np
import pytest

import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from photon_ml_tpu.data.index_map import IndexMap, feature_key
from photon_ml_tpu.data.reader import EntityIndex
from photon_ml_tpu.models.game import (FixedEffectModel, GameModel,
                                       RandomEffectModel)
from photon_ml_tpu.models.glm import Coefficients
from photon_ml_tpu.online.catchup import LogFollower
from photon_ml_tpu.online.delta_log import DeltaLog, DeltaRecord
from photon_ml_tpu.online.replication import (ReplicationClient,
                                              ReplicationClientConfig,
                                              ReplicationConfig,
                                              ReplicationServer,
                                              attach_replication)
from photon_ml_tpu.online.replication.snapshot import (SnapshotError,
                                                       pack_model_dir,
                                                       unpack_snapshot)
from photon_ml_tpu.online.replication.wire import (WireError,
                                                   decode_record_obj,
                                                   encode_record_line,
                                                   parse_identity, parse_line)
from photon_ml_tpu.serving.batcher import request_from_json
from photon_ml_tpu.types import TaskType

N_ENT = 12
D = 3
NAMES = [f"f{j}" for j in range(D)]


def _save_model_dir(path, seed=0):
    from photon_ml_tpu.storage.model_io import save_game_model

    rng = np.random.default_rng(seed)
    task = TaskType.LOGISTIC_REGRESSION
    model = GameModel(models={
        "fixed": FixedEffectModel(
            coefficients=Coefficients(means=rng.normal(size=D)),
            feature_shard="all", task=task),
        "user": RandomEffectModel(
            w_stack=rng.normal(size=(N_ENT, D)) * 0.5,
            slot_of={i: i for i in range(N_ENT)},
            random_effect_type="userId", feature_shard="all", task=task),
    })
    imap = IndexMap({feature_key(n): j for j, n in enumerate(NAMES)})
    eidx = EntityIndex()
    for i in range(N_ENT):
        eidx.get_or_add(f"user{i}")
    save_game_model(model, path, {"all": imap}, {"userId": eidx}, task=task)
    imap.save(os.path.join(path, "all.idx"))
    eidx.save(os.path.join(path, "userId.entities.json"))
    return path


def _probes():
    rng = np.random.default_rng(99)
    out = []
    for i in range(N_ENT):
        out.append(request_from_json({
            "uid": i,
            "features": [[n, float(v)]
                         for n, v in zip(NAMES, rng.normal(size=D))],
            "ids": {"userId": f"user{i}"}}))
    return out


def _scores(engine):
    return [float(s) for s in engine.score_requests(_probes())]


def _wait(pred, timeout=15.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.01)
    raise AssertionError(f"timed out waiting for {msg}")


def _rec(g, v, entity="user1"):
    return DeltaRecord(generation=g, delta_version=v, cid="user",
                       entity=entity,
                       row=tuple(float(j) + 0.5 * v for j in range(D)))


class _Owner:
    """In-process photonrepl owner: engine + owning swapper + log server."""

    def __init__(self, tmp_path, warm=False, auth_token=None,
                 config_kwargs=None):
        from photon_ml_tpu.cli.serve import build_server

        self.base_dir = _save_model_dir(str(tmp_path / "base"), seed=0)
        self.log = DeltaLog(str(tmp_path / "owner-log"), fsync="rotate")
        self.engine, self.swapper = build_server(
            self.base_dir, max_batch=4, warm=warm,
            delta_log=self.log, log_owner=True)
        kw = dict(config_kwargs or {})
        kw.setdefault("auth_token", auth_token)
        self.repl = attach_replication(
            self.swapper, ReplicationConfig(**kw),
            registry=self.engine.metrics.registry)
        self.port = self.repl.port
        self.registry = self.engine.metrics.registry

    def publish(self, n=1, seed=1):
        rng = np.random.default_rng(seed)
        dim = self.engine.store.coordinates["user"].dim
        out = []
        for _ in range(n):
            ent = f"user{int(rng.integers(0, N_ENT))}"
            identity = self.swapper.publish_delta(
                "user", ent, rng.normal(size=dim))
            assert identity is not None
            out.append(identity)
        return out

    def swap(self, tmp_path, name, seed):
        new_dir = _save_model_dir(str(tmp_path / name), seed=seed)
        assert self.swapper.swap(new_dir) is True
        return new_dir

    def close(self):
        self.repl.stop()
        self.log.close()


class _Replica:
    """Replica: client + spool + engine fed by the mirror (serve.py
    --subscribe wiring, in-process)."""

    def __init__(self, owner_port, spool, warm=False, auth_token=None,
                 ack_every=1, bootstrap_timeout=20.0):
        from photon_ml_tpu.cli.serve import build_server
        from photon_ml_tpu.serving.metrics import ServingMetrics

        self.metrics = ServingMetrics()
        self.client = ReplicationClient(
            ReplicationClientConfig(host="127.0.0.1", port=owner_port,
                                    spool_dir=str(spool),
                                    auth_token=auth_token,
                                    ack_every=ack_every,
                                    ack_interval_s=0.05,
                                    backoff_initial_s=0.05),
            registry=self.metrics.registry).start()
        model_dir = self.client.bootstrap(timeout=bootstrap_timeout)
        self.mirror = DeltaLog(self.client.mirror_path, fsync="never")
        self.engine, self.swapper = build_server(
            model_dir, max_batch=4, warm=warm, metrics=self.metrics,
            delta_log=self.mirror, log_owner=False)
        self.swapper.set_base(model_dir, self.client.floor or 0)
        self.client.on_snapshot = \
            lambda d, g: self.swapper.swap(d, replay_floor=g)
        if self.client.model_dir != model_dir:
            self.swapper.swap(self.client.model_dir,
                              replay_floor=self.client.floor)
        self.follower = LogFollower(self.mirror, lambda: self.engine.store,
                                    poll_interval_s=0.01,
                                    registry=self.metrics.registry)
        self.follower.run_once()
        self.follower.start()

    def converge_to(self, identity, timeout=15.0):
        """Wait until the mirror AND the serving store reach ``identity``."""
        _wait(lambda: self.client.last_identity == identity,
              timeout, f"mirror at {identity}")
        _wait(lambda: self.follower.position == identity,
              timeout, f"store at {identity}")

    def close(self):
        self.follower.stop()
        self.client.stop()
        self.mirror.close()


# ---------------------------------------------------------------------------
# wire framing
# ---------------------------------------------------------------------------
class TestWire:
    def test_record_line_round_trips_bitwise(self):
        rec = _rec(3, 7, entity="userX")
        line = encode_record_line(rec)
        obj = json.loads(line.decode("utf-8"))
        assert obj["repl"] == "delta"
        got = decode_record_obj(obj)
        assert got == rec  # frozen dataclass equality: rows bitwise too
        # the wire payload IS the on-disk frame payload
        assert obj["p"].encode("utf-8") == rec.encode()[8:]

    def test_tampered_payload_rejected(self):
        obj = json.loads(encode_record_line(_rec(1, 1)).decode("utf-8"))
        obj["p"] = obj["p"].replace("user1", "user2")
        with pytest.raises(WireError, match="CRC32"):
            decode_record_obj(obj)

    def test_malformed_delta_frames(self):
        with pytest.raises(WireError):
            decode_record_obj({"repl": "delta"})
        with pytest.raises(WireError):
            decode_record_obj({"repl": "delta", "p": "x", "crc": "nan"})

    def test_parse_identity(self):
        assert parse_identity(None) is None
        assert parse_identity([3, 4]) == (3, 4)
        with pytest.raises(WireError):
            parse_identity("nope")
        with pytest.raises(WireError):
            parse_identity([1, 2, 3])

    def test_parse_line(self):
        assert parse_line(b'{"a": 1}') == {"a": 1}
        with pytest.raises(WireError):
            parse_line(b"[1, 2]")
        with pytest.raises(WireError):
            parse_line(b"{nope")


# ---------------------------------------------------------------------------
# snapshot tarstream
# ---------------------------------------------------------------------------
class TestSnapshot:
    def test_round_trip_and_determinism(self, tmp_path):
        src = _save_model_dir(str(tmp_path / "m"))
        data1, crc1 = pack_model_dir(src)
        # mtime churn must not change the bytes (CRC is an identity, not
        # an mtime lottery)
        for root, _, files in os.walk(src):
            for f in files:
                os.utime(os.path.join(root, f))
        data2, crc2 = pack_model_dir(src)
        assert data1 == data2 and crc1 == crc2
        dest = str(tmp_path / "out")
        unpack_snapshot(data1, crc1, dest)
        walk = {os.path.relpath(os.path.join(r, f), dest)
                for r, _, fs in os.walk(dest) for f in fs}
        src_walk = {os.path.relpath(os.path.join(r, f), src)
                    for r, _, fs in os.walk(src) for f in fs}
        assert walk == src_walk
        for rel in src_walk:
            with open(os.path.join(src, rel), "rb") as a, \
                    open(os.path.join(dest, rel), "rb") as b:
                assert a.read() == b.read()

    def test_crc_mismatch_rejected(self, tmp_path):
        data, crc = pack_model_dir(_save_model_dir(str(tmp_path / "m")))
        with pytest.raises(SnapshotError, match="CRC32"):
            unpack_snapshot(data, crc ^ 1, str(tmp_path / "out"))

    def test_traversal_member_rejected(self, tmp_path):
        import io
        import tarfile
        import zlib

        buf = io.BytesIO()
        with tarfile.open(fileobj=buf, mode="w") as tf:
            info = tarfile.TarInfo("../evil.txt")
            info.size = 4
            tf.addfile(info, io.BytesIO(b"boom"))
        data = buf.getvalue()
        with pytest.raises(SnapshotError, match="escapes"):
            unpack_snapshot(data, zlib.crc32(data), str(tmp_path / "out"))
        assert not os.path.exists(str(tmp_path / "evil.txt"))

    def test_link_member_rejected(self, tmp_path):
        import io
        import tarfile
        import zlib

        buf = io.BytesIO()
        with tarfile.open(fileobj=buf, mode="w") as tf:
            info = tarfile.TarInfo("link")
            info.type = tarfile.SYMTYPE
            info.linkname = "/etc/passwd"
            tf.addfile(info)
        data = buf.getvalue()
        with pytest.raises(SnapshotError):
            unpack_snapshot(data, zlib.crc32(data), str(tmp_path / "out"))

    def test_missing_source_rejected(self, tmp_path):
        with pytest.raises(SnapshotError, match="not a directory"):
            pack_model_dir(str(tmp_path / "nope"))


# ---------------------------------------------------------------------------
# bootstrap + live tail (the tentpole end-to-end)
# ---------------------------------------------------------------------------
class TestBootstrapAndTail:
    def test_snapshot_bootstrap_converges_bitwise(self, tmp_path):
        owner = _Owner(tmp_path)
        try:
            owner.publish(5, seed=1)  # pre-connect history
            rep = _Replica(owner.port, tmp_path / "spool")
            try:
                assert rep.client.last_resume_mode == "snapshot"
                last = owner.publish(5, seed=2)[-1]  # live tail
                rep.converge_to(last)
                assert _scores(rep.engine) == _scores(owner.engine)
                assert owner.registry.counter("repl_snapshots_total") == 1
            finally:
                rep.close()
        finally:
            owner.close()

    def test_zero_recompiles_after_warm(self, tmp_path):
        owner = _Owner(tmp_path, warm=True)
        try:
            rep = _Replica(owner.port, tmp_path / "spool", warm=True)
            try:
                compiles = rep.engine.compile_count
                _scores(rep.engine)  # bucket ladder exercised once
                compiles = rep.engine.compile_count
                last = owner.publish(8, seed=3)[-1]
                rep.converge_to(last)
                assert _scores(rep.engine) == _scores(owner.engine)
                # streamed rows are in-place scatters: no recompile, ever
                assert rep.engine.compile_count == compiles
            finally:
                rep.close()
        finally:
            owner.close()

    def test_reconnect_resumes_via_log(self, tmp_path):
        owner = _Owner(tmp_path)
        try:
            owner.publish(3, seed=1)
            rep = _Replica(owner.port, tmp_path / "spool")
            last = owner.publish(2, seed=2)[-1]
            rep.converge_to(last)
            rep.close()

            more = owner.publish(4, seed=3)[-1]  # while replica is down
            rep2 = _Replica(owner.port, tmp_path / "spool")
            try:
                assert rep2.client.last_resume_mode == "log"
                rep2.converge_to(more)
                assert _scores(rep2.engine) == _scores(owner.engine)
                assert owner.registry.counter("repl_resume_total",
                                              mode="log") == 1
            finally:
                rep2.close()
        finally:
            owner.close()

    def test_compacted_past_resume_falls_back_to_snapshot(self, tmp_path):
        owner = _Owner(tmp_path)
        try:
            owner.publish(3, seed=1)
            rep = _Replica(owner.port, tmp_path / "spool")
            last = owner.publish(1, seed=2)[-1]
            rep.converge_to(last)
            rep.close()

            # owner swaps with no follower connected: compaction passes
            # the replica's identity and its floor is stale
            owner.swap(tmp_path, "base2", seed=2)
            post = owner.publish(2, seed=4)[-1]
            rep2 = _Replica(owner.port, tmp_path / "spool")
            try:
                # warm-spool bootstrap() returns from state.json at once;
                # the fresh snapshot lands asynchronously
                _wait(lambda: rep2.client.snapshots_received >= 1,
                      msg="snapshot fallback")
                assert rep2.client.last_resume_mode == "snapshot"
                assert rep2.client.floor == owner.swapper.replay_floor
                rep2.converge_to(post)
                assert _scores(rep2.engine) == _scores(owner.engine)
            finally:
                rep2.close()
        finally:
            owner.close()

    def test_in_stream_owner_swap_ships_snapshot(self, tmp_path):
        owner = _Owner(tmp_path)
        try:
            rep = _Replica(owner.port, tmp_path / "spool")
            try:
                pre = owner.publish(3, seed=1)[-1]
                rep.converge_to(pre)
                owner.swap(tmp_path, "base2", seed=2)
                post = owner.publish(3, seed=5)[-1]
                _wait(lambda: rep.client.snapshots_received >= 2,
                      msg="mid-stream snapshot")
                rep.converge_to(post)
                assert rep.client.floor == owner.swapper.replay_floor
                assert _scores(rep.engine) == _scores(owner.engine)
                # the replica hot-swapped: its serving base is the shipped
                # dir, not the bootstrap extract
                assert rep.swapper.replay_floor == owner.swapper.replay_floor
            finally:
                rep.close()
        finally:
            owner.close()


# ---------------------------------------------------------------------------
# retention floor + eviction policy
# ---------------------------------------------------------------------------
class TestRetention:
    def test_connected_follower_pins_compaction(self, tmp_path):
        owner = _Owner(tmp_path)
        try:
            rep = _Replica(owner.port, tmp_path / "spool")
            try:
                last = owner.publish(3, seed=1)[-1]
                rep.converge_to(last)
                _wait(lambda: owner.log.min_retained_generation() is not None,
                      msg="segment on disk")
                gen_before = last[0]
                # swap compacts — but the follower's acked identity is
                # still on the old generation when compact runs (the swap
                # raises the base floor only AFTER compaction), so the old
                # segment must survive
                owner.swap(tmp_path, "base2", seed=2)
                assert owner.log.min_retained_generation() == gen_before
                # once the follower converges onto the new base, the next
                # swap is free to drop the old lineage
                post = owner.publish(1, seed=6)[-1]
                _wait(lambda: rep.client.snapshots_received >= 2,
                      msg="mid-stream snapshot")
                rep.converge_to(post)
                # the ack travels the socket asynchronously: wait for the
                # owner's pin view to reflect it before compacting again
                srv = owner.repl.server
                _wait(lambda: all(p is not None and p >= post[0]
                                  for p, _ in srv._pin_view.values()),
                      msg="ack to reach the owner's pin view")
                owner.swap(tmp_path, "base3", seed=3)
                mrg = owner.log.min_retained_generation()
                assert mrg is None or mrg > gen_before
            finally:
                rep.close()
        finally:
            owner.close()

    def test_byte_cap_evicts_worst_pinner(self, tmp_path):
        """Unit-level: retention_floor applies the byte cap by evicting
        the minimum pinner until the pinned segments fit."""
        log = DeltaLog(str(tmp_path / "log"), fsync="never")
        for g in (1, 2, 3):
            for v in (1, 2):
                log.append(_rec(g, v))
        srv = ReplicationServer(log, ReplicationConfig(pin_byte_cap=1))
        srv._base_generation = 4
        now = time.monotonic()
        srv._pin_view = {1: (1, now), 2: (3, now)}
        # fid 1 pins gens [1, 4) — way past 1 byte — and is evicted; fid 2
        # pins [3, 4), also over the 1-byte cap, so nothing pins
        assert srv.retention_floor() is None
        assert srv._pin_view == {}

        srv2 = ReplicationServer(log, ReplicationConfig(pin_byte_cap=1 << 20))
        srv2._base_generation = 4
        srv2._pin_view = {1: (2, now), 2: (3, now)}
        assert srv2.retention_floor() == 2  # min pin, within budget

    def test_age_cap_drops_stale_pinner(self, tmp_path):
        log = DeltaLog(str(tmp_path / "log"), fsync="never")
        log.append(_rec(1, 1))
        srv = ReplicationServer(log, ReplicationConfig(pin_age_cap_s=0.01))
        srv._base_generation = 3
        srv._pin_view = {7: (1, time.monotonic() - 1.0)}
        assert srv.retention_floor() is None  # stale ack: pin ignored
        assert 7 not in srv._pin_view

    def test_compaction_respects_pin_floor(self, tmp_path):
        log = DeltaLog(str(tmp_path / "log"), fsync="never")
        for g in (1, 2, 3):
            log.append(_rec(g, 1))
        log.retention_pin = lambda: 2
        dropped = log.compact(3)
        assert dropped == [1]
        assert [g for g, _ in log.segments()] == [2, 3]
        log.retention_pin = None
        assert log.compact(3) == [2]


# ---------------------------------------------------------------------------
# backpressure: queue overflow falls back to log catch-up
# ---------------------------------------------------------------------------
class TestBackpressure:
    def test_queue_overflow_catches_up_from_log(self, tmp_path):
        owner = _Owner(tmp_path, config_kwargs={"queue_records": 2})
        try:
            rep = _Replica(owner.port, tmp_path / "spool")
            try:
                # burst far past the 2-record queue bound: overflowed
                # records MUST still arrive (re-read from the durable log)
                last = owner.publish(40, seed=1)[-1]
                rep.converge_to(last)
                assert _scores(rep.engine) == _scores(owner.engine)
                assert rep.client.records_applied == 40
            finally:
                rep.close()
        finally:
            owner.close()


# ---------------------------------------------------------------------------
# auth (satellite: replication socket AND serving front end)
# ---------------------------------------------------------------------------
class TestAuth:
    def test_repl_socket_requires_token(self, tmp_path):
        owner = _Owner(tmp_path, auth_token="sekrit")
        try:
            bad = ReplicationClient(ReplicationClientConfig(
                host="127.0.0.1", port=owner.port,
                spool_dir=str(tmp_path / "bad-spool"),
                auth_token="wrong", backoff_initial_s=0.05)).start()
            with pytest.raises(RuntimeError, match="unauthorized"):
                bad.bootstrap(timeout=1.5)
            bad.stop()
            fails = owner.registry.counter_series("repl_auth_failures_total")
            assert sum(fails.values()) >= 1

            rep = _Replica(owner.port, tmp_path / "spool",
                           auth_token="sekrit")
            try:
                last = owner.publish(2, seed=1)[-1]
                rep.converge_to(last)
                assert _scores(rep.engine) == _scores(owner.engine)
            finally:
                rep.close()
        finally:
            owner.close()

    def test_frontend_requires_token(self, tmp_path):
        from photon_ml_tpu.cli.serve import build_server
        from photon_ml_tpu.serving.frontend import (FrontendConfig,
                                                    ThreadedFrontend)

        base = _save_model_dir(str(tmp_path / "m"))
        engine, swapper = build_server(base, max_batch=4, warm=False)
        tf = ThreadedFrontend(engine, swapper,
                              FrontendConfig(auth_token="sekrit")).start()
        try:
            probe = {"uid": 0, "features": [[n, 0.5] for n in NAMES],
                     "ids": {"userId": "user1"}}

            def _talk(lines):
                sock = socket.create_connection(("127.0.0.1", tf.port),
                                                timeout=10)
                f = sock.makefile("rw", encoding="utf-8", newline="\n")
                for obj in lines:
                    f.write(json.dumps(obj) + "\n")
                f.flush()
                out = []
                try:
                    for line in f:
                        out.append(json.loads(line))
                except (OSError, ValueError):
                    pass
                sock.close()
                return out

            # no auth line: one unauthorized frame, then the close
            replies = _talk([probe])
            assert replies == [{"error": "unauthorized"}]
            # wrong token: same
            replies = _talk([{"cmd": "auth", "token": "nope"}, probe])
            assert replies == [{"error": "unauthorized"}]
            # right token: {"auth": "ok"} then normal scoring
            replies = _talk([{"cmd": "auth", "token": "sekrit"}, probe,
                             {"cmd": "shutdown"}])
            assert replies[0] == {"auth": "ok"}
            assert "score" in replies[1]
            fails = engine.metrics.registry.counter_series(
                "front_auth_failures_total")
            assert sum(fails.values()) == 2
        finally:
            tf.stop()


# ---------------------------------------------------------------------------
# chaos: torn tail + owner restart + compaction + follower resume
# ---------------------------------------------------------------------------
class TestChaos:
    def test_torn_tail_restart_compact_resume_one_chain(self, tmp_path):
        from photon_ml_tpu.serving.coefficient_store import \
            advance_generation_floor

        owner = _Owner(tmp_path)
        try:
            owner.publish(4, seed=1)
            rep = _Replica(owner.port, tmp_path / "spool")
            last = owner.publish(2, seed=2)[-1]
            rep.converge_to(last)
            rep.close()
        finally:
            owner.close()

        # tear the newest segment's tail (crash mid-append)
        segs = DeltaLog(str(tmp_path / "owner-log"), fsync="never").segments()
        with open(segs[-1][1], "ab") as f:
            f.write(b"\x99\x00\x00\x00torn")

        # owner restarts on the torn log: resume past the last DURABLE
        # identity (learn.py's restart protocol)
        log2 = DeltaLog(str(tmp_path / "owner-log"), fsync="rotate")
        durable_last = log2.last_identity()
        assert durable_last == last  # the tear cost nothing committed
        advance_generation_floor(durable_last[0] + 1)

        from photon_ml_tpu.cli.serve import build_server

        base2 = _save_model_dir(str(tmp_path / "restart-base"), seed=0)
        engine2, swapper2 = build_server(base2, max_batch=4, warm=False,
                                         delta_log=log2, log_owner=True)
        repl2 = attach_replication(swapper2, ReplicationConfig(),
                                   registry=engine2.metrics.registry)
        try:
            rng = np.random.default_rng(8)
            dim = engine2.store.coordinates["user"].dim
            for _ in range(3):
                assert swapper2.publish_delta(
                    "user", f"user{int(rng.integers(0, N_ENT))}",
                    rng.normal(size=dim)) is not None
            # swap → compaction passes the replica's floor entirely
            new_dir = _save_model_dir(str(tmp_path / "base-after"), seed=3)
            assert swapper2.swap(new_dir) is True
            final = swapper2.publish_delta("user", "user1",
                                           rng.normal(size=dim))

            rep2 = _Replica(repl2.port, tmp_path / "spool")
            try:
                _wait(lambda: rep2.client.snapshots_received >= 1,
                      msg="snapshot fallback")
                assert rep2.client.last_resume_mode == "snapshot"
                rep2.converge_to(final)
                # one identity chain: the mirror's records are exactly the
                # owner's retained records, in order
                mirror = [r.identity for r in rep2.mirror.replay()]
                owner_log = [r.identity for r in log2.replay()]
                assert mirror == [i for i in owner_log
                                  if i >= (swapper2.replay_floor, 0)]
                assert mirror == sorted(mirror)
                assert _scores(rep2.engine) == _scores(engine2)
            finally:
                rep2.close()
        finally:
            repl2.stop()
            log2.close()

    def test_crash_between_model_write_and_activate_one_chain(
            self, tmp_path):
        """Satellite 3: kill the owner BETWEEN the model-dir write and
        ``activate`` (the ``swap.activate`` fault point), restart it on
        the fully-written dir, and assert the replica converges on ONE
        identity chain bitwise — the crashed swap must neither fork the
        chain nor lose the deltas published around it."""
        from photon_ml_tpu.chaos import InjectedCrash, get_injector
        from photon_ml_tpu.serving.coefficient_store import \
            advance_generation_floor

        inj = get_injector()
        new_dir = None
        owner = _Owner(tmp_path)
        try:
            owner.publish(3, seed=1)
            rep = _Replica(owner.port, tmp_path / "spool")
            try:
                rep.converge_to(owner.publish(2, seed=2)[-1])

                # the new model dir lands on disk in full; the crash hits
                # just before the generation flip
                new_dir = _save_model_dir(str(tmp_path / "gen-next"),
                                          seed=5)
                before = owner.swapper.identity
                inj.arm("swap.activate", "crash", max_fires=1)
                try:
                    with pytest.raises(InjectedCrash):
                        owner.swapper.swap(new_dir)
                finally:
                    inj.reset()
                # the old generation keeps serving, no identity burned,
                # and publishes continue on the SAME chain
                assert owner.swapper.identity == before
                more = owner.publish(2, seed=3)[-1]
                rep.converge_to(more)
                assert _scores(rep.engine) == _scores(owner.engine)
            finally:
                rep.close()
        finally:
            owner.close()

        # owner restarts.  The crashed swap never ACTIVATED new_dir, so
        # the authoritative restart base is the OLD one: come back on it,
        # replay the retained log (learn.py restart protocol), then RETRY
        # the swap — the dir the crash left behind is fully written, and
        # the retry activates it under a fresh generation that every
        # follower learns about through the snapshot broadcast
        log2 = DeltaLog(str(tmp_path / "owner-log"), fsync="rotate")
        durable_last = log2.last_identity()
        assert durable_last == more  # the crash cost nothing committed
        advance_generation_floor(durable_last[0] + 1)

        from photon_ml_tpu.cli.serve import build_server

        engine2, swapper2 = build_server(
            str(tmp_path / "base"), max_batch=4, warm=False,
            delta_log=log2, log_owner=True)
        LogFollower(log2, lambda: engine2.store).run_once()
        repl2 = attach_replication(swapper2, ReplicationConfig(),
                                   registry=engine2.metrics.registry)
        try:
            assert swapper2.swap(new_dir) is True  # the retry completes
            rng = np.random.default_rng(11)
            dim = engine2.store.coordinates["user"].dim
            final = None
            for _ in range(3):
                final = swapper2.publish_delta(
                    "user", f"user{int(rng.integers(0, N_ENT))}",
                    rng.normal(size=dim))
                assert final is not None

            rep2 = _Replica(repl2.port, tmp_path / "spool")
            try:
                rep2.converge_to(final)
                # ONE identity chain, bitwise: the mirror is strictly
                # monotone, every record is the owner's record verbatim,
                # and it ends at the owner's tail
                mirror = list(rep2.mirror.replay())
                m_ids = [r.identity for r in mirror]
                assert m_ids == sorted(m_ids)
                assert len(set(m_ids)) == len(m_ids)
                assert m_ids[-1] == final
                owner_by_id = {r.identity: r for r in log2.replay()}
                for r in mirror:
                    assert r == owner_by_id[r.identity]  # bitwise rows
                assert _scores(rep2.engine) == _scores(engine2)
            finally:
                rep2.close()
        finally:
            repl2.stop()
            log2.close()


# ---------------------------------------------------------------------------
# serve.py --subscribe end to end
# ---------------------------------------------------------------------------
class TestServeSubscribeCli:
    def test_subscribe_scores_match_owner(self, tmp_path, capsys):
        from photon_ml_tpu.cli import serve as serve_cli

        owner = _Owner(tmp_path)
        try:
            # no deltas in flight: the run() process serves right after its
            # initial catch-up, so parity is only deterministic against a
            # quiescent owner (live-tail convergence is covered above)
            probe = {"uid": 0, "features": [[n, 0.5] for n in NAMES],
                     "ids": {"userId": "user3"}}
            want = float(owner.engine.score_requests(
                [request_from_json(probe)])[0])

            req_file = tmp_path / "req.jsonl"
            req_file.write_text(json.dumps(probe) + "\n")
            rc = serve_cli.run(["--subscribe", f"127.0.0.1:{owner.port}",
                                "--spool", str(tmp_path / "cli-spool"),
                                "--no-warm", "--requests", str(req_file)])
            assert rc == 0
            out = capsys.readouterr().out.strip().splitlines()
            assert json.loads(out[0])["score"] == want
        finally:
            owner.close()

    def test_subscribe_flag_validation(self, tmp_path):
        from photon_ml_tpu.cli import serve as serve_cli

        # --subscribe needs --spool
        assert serve_cli.run(["--subscribe", "127.0.0.1:1"]) == 1
        # --subscribe excludes --model-dir / --delta-log
        assert serve_cli.run(["--subscribe", "127.0.0.1:1",
                              "--spool", str(tmp_path / "s"),
                              "--model-dir", str(tmp_path)]) == 1
        # neither --model-dir nor --subscribe
        assert serve_cli.run(["--requests", "/dev/null"]) == 1


# ---------------------------------------------------------------------------
# learn.py --repl-listen wiring
# ---------------------------------------------------------------------------
class TestLearnCliRepl:
    def test_repl_listen_requires_delta_log(self, tmp_path):
        from photon_ml_tpu.cli import learn as learn_cli

        base = _save_model_dir(str(tmp_path / "m"))
        rc = learn_cli.run(["--model-dir", base,
                            "--repl-listen", "127.0.0.1:0",
                            "--examples", "/dev/null"])
        assert rc == 1

    def test_parse_hostport(self):
        from photon_ml_tpu.cli.learn import _parse_hostport

        assert _parse_hostport("0.0.0.0:712") == ("0.0.0.0", 712)
        with pytest.raises(ValueError):
            _parse_hostport("712")


class TestSnapshotOffLoop:
    """Regression: snapshot unpack / old-base deletion must not run ON the
    client's event loop (they scale with model size and used to stall the
    stream's acks and heartbeats for the whole extraction)."""

    def test_unpack_runs_off_the_event_loop(self, tmp_path, monkeypatch):
        import asyncio
        import threading

        from photon_ml_tpu.online.replication import client as client_mod

        cl = ReplicationClient(
            ReplicationClientConfig(host="127.0.0.1", port=1,
                                    spool_dir=str(tmp_path / "spool")))
        unpack_threads = []

        def slow_unpack(data, crc, dest):
            unpack_threads.append(threading.current_thread())
            time.sleep(0.3)  # a big model extracting
            os.makedirs(dest, exist_ok=True)

        monkeypatch.setattr(client_mod, "unpack_snapshot", slow_unpack)

        class _FakeReader:
            async def readexactly(self, n):
                return b"x" * n

        ticks = []

        async def main():
            async def ticker():
                while True:
                    ticks.append(time.monotonic())
                    await asyncio.sleep(0.01)

            t = asyncio.ensure_future(ticker())
            await asyncio.sleep(0)  # let the ticker start
            await cl._take_snapshot(
                _FakeReader(), {"bytes": 8, "crc32": 0, "generation": 3})
            t.cancel()

        try:
            asyncio.run(main())
        finally:
            cl._mirror.close()

        # the unpack ran in an executor worker, not the loop thread ...
        assert unpack_threads and \
            unpack_threads[0] is not threading.main_thread()
        # ... so the loop kept serving other coroutines throughout the
        # 0.3s extraction (a blocking unpack yields ~1 tick, not dozens)
        assert len(ticks) >= 10, f"loop starved: {len(ticks)} tick(s)"
        # and the snapshot still landed
        assert cl.floor == 3
        assert cl.model_dir is not None and os.path.isdir(cl.model_dir)
        assert cl._bootstrapped.is_set()
