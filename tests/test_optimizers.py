"""Optimizer-kernel tests.

Reference analog: OptimizerIntegTest with a known-minimum objective
(photon-lib integTest) — here scipy.optimize is the golden reference, plus
vmap (batched-entity) semantics that the reference has no analog for.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import scipy.optimize as sopt

from photon_ml_tpu.core import GLMObjective, Regularization, losses
from photon_ml_tpu.core.batch import dense_batch
from photon_ml_tpu.opt import (
    SolverConfig,
    box_arrays,
    make_solver,
    minimize_lbfgs,
    minimize_owlqn,
    minimize_tron,
)
from photon_ml_tpu.opt.solve import compute_variances
from photon_ml_tpu.types import ConvergenceReason, OptimizerType, VarianceComputationType

D = 6


def _logistic_problem(rng, n=200, d=D, l2=0.1, seed_shift=0.0):
    x = rng.normal(size=(n, d)) + seed_shift
    w_true = rng.normal(size=d)
    p = 1.0 / (1.0 + np.exp(-(x @ w_true)))
    y = (rng.random(n) < p).astype(float)
    batch = dense_batch(x, y)
    obj = GLMObjective(loss=losses.logistic_loss, reg=Regularization(l2=l2))
    return obj, batch


def _scipy_min(obj, batch, d=D):
    f = lambda w: np.asarray(obj.value(jnp.asarray(w), batch))
    g = lambda w: np.asarray(obj.gradient(jnp.asarray(w), batch))
    res = sopt.minimize(f, np.zeros(d), jac=g, method="L-BFGS-B",
                        options={"maxiter": 500, "ftol": 1e-15, "gtol": 1e-12})
    return res


def test_lbfgs_matches_scipy(rng):
    obj, batch = _logistic_problem(rng)
    solve = make_solver(obj, OptimizerType.LBFGS)
    res = jax.jit(solve)(jnp.zeros(D), batch)
    ref = _scipy_min(obj, batch)
    np.testing.assert_allclose(res.value, ref.fun, rtol=1e-8)
    np.testing.assert_allclose(res.w, ref.x, rtol=1e-4, atol=1e-6)
    assert res.convergence_reason() in (
        ConvergenceReason.FUNCTION_VALUES_CONVERGED,
        ConvergenceReason.GRADIENT_CONVERGED,
    )


def test_lbfgs_quadratic_exact(rng):
    """On a quadratic, L-BFGS must hit the known minimum fast."""
    a = rng.normal(size=(D, D))
    h = a @ a.T + np.eye(D)
    b = rng.normal(size=D)
    w_star = np.linalg.solve(h, b)
    hj, bj = jnp.asarray(h), jnp.asarray(b)

    def vg(w):
        return 0.5 * w @ hj @ w - bj @ w, hj @ w - bj

    res = minimize_lbfgs(vg, jnp.zeros(D), SolverConfig(max_iters=100, tolerance=1e-12))
    np.testing.assert_allclose(res.w, w_star, rtol=1e-6, atol=1e-8)
    assert int(res.iterations) < 30


def test_tron_matches_scipy(rng):
    obj, batch = _logistic_problem(rng)
    solve = make_solver(obj, OptimizerType.TRON,
                        SolverConfig(max_iters=50, tolerance=1e-10, max_cg=20))
    res = jax.jit(solve)(jnp.zeros(D), batch)
    ref = _scipy_min(obj, batch)
    np.testing.assert_allclose(res.value, ref.fun, rtol=1e-9)
    np.testing.assert_allclose(res.w, ref.x, rtol=1e-4, atol=1e-6)


def test_tron_poisson(rng):
    x = rng.normal(size=(150, D)) * 0.3
    y = rng.poisson(1.5, size=150).astype(float)
    batch = dense_batch(x, y)
    obj = GLMObjective(loss=losses.poisson_loss, reg=Regularization(l2=0.5))
    res = jax.jit(make_solver(obj, OptimizerType.TRON,
                              SolverConfig(max_iters=50, tolerance=1e-10)))(jnp.zeros(D), batch)
    ref = _scipy_min(obj, batch)
    np.testing.assert_allclose(res.value, ref.fun, rtol=1e-8)


def test_owlqn_l1_sparsity_and_value(rng):
    obj, batch = _logistic_problem(rng, l2=0.0)
    l1 = 12.0
    obj = obj.replace(reg=Regularization(l1=l1))
    solve = make_solver(obj, OptimizerType.LBFGS)  # auto-routes to OWLQN
    res = jax.jit(solve)(jnp.zeros(D), batch)

    # scipy reference: smooth + l1 via double-variable trick w = p - n, p,n >= 0
    def f(z):
        w = z[:D] - z[D:]
        return float(obj.raw_value(jnp.asarray(w), batch)) + l1 * z.sum()

    def g(z):
        w = jnp.asarray(z[:D] - z[D:])
        gs = np.asarray(obj.gradient(w, batch)) - 0.0  # no l2
        return np.concatenate([gs + l1, -gs + l1])

    ref = sopt.minimize(f, np.zeros(2 * D), jac=g, method="L-BFGS-B",
                        bounds=[(0, None)] * (2 * D), options={"maxiter": 1000, "ftol": 1e-15})
    np.testing.assert_allclose(res.value, ref.fun, rtol=1e-6)
    # strong L1 must produce some exact zeros
    assert int(jnp.sum(res.w == 0.0)) > 0


def test_box_constraints(rng):
    obj, batch = _logistic_problem(rng)
    box = box_arrays({0: (-0.05, 0.05), 3: (0.0, np.inf)}, D, np.float64)
    solve = make_solver(obj, OptimizerType.LBFGS, box=(jnp.asarray(box[0]), jnp.asarray(box[1])))
    res = jax.jit(solve)(jnp.zeros(D), batch)
    assert -0.05 <= float(res.w[0]) <= 0.05
    assert float(res.w[3]) >= 0.0
    ref = sopt.minimize(
        lambda w: np.asarray(obj.value(jnp.asarray(w), batch)),
        np.zeros(D),
        jac=lambda w: np.asarray(obj.gradient(jnp.asarray(w), batch)),
        method="L-BFGS-B",
        bounds=[(-0.05, 0.05), (None, None), (None, None), (0.0, None), (None, None), (None, None)],
        options={"maxiter": 500, "ftol": 1e-15},
    )
    np.testing.assert_allclose(res.value, ref.fun, rtol=1e-5)


def test_vmap_batched_entities(rng):
    """The random-effect shape: vmap the SAME solver over many entity problems
    with different data; each lane must match its own scipy solve."""
    n_entities, n, d = 5, 40, 4
    xs = rng.normal(size=(n_entities, n, d))
    ws = rng.normal(size=(n_entities, d))
    ys = (rng.random((n_entities, n)) < 1.0 / (1.0 + np.exp(-np.einsum("end,ed->en", xs, ws)))).astype(float)
    obj = GLMObjective(loss=losses.logistic_loss, reg=Regularization(l2=0.3))
    solve = make_solver(obj, OptimizerType.LBFGS, SolverConfig(max_iters=200, tolerance=1e-9))

    def solve_one(x, y):
        return solve(jnp.zeros(d), dense_batch(x, y))

    res = jax.jit(jax.vmap(solve_one))(jnp.asarray(xs), jnp.asarray(ys))
    for e in range(n_entities):
        batch_e = dense_batch(xs[e], ys[e])
        ref = sopt.minimize(
            lambda w: np.asarray(obj.value(jnp.asarray(w), batch_e)),
            np.zeros(d),
            jac=lambda w: np.asarray(obj.gradient(jnp.asarray(w), batch_e)),
            method="L-BFGS-B", options={"maxiter": 500, "ftol": 1e-15},
        )
        np.testing.assert_allclose(res.value[e], ref.fun, rtol=1e-8)
        np.testing.assert_allclose(res.w[e], ref.x, rtol=1e-3, atol=1e-5)


def test_convergence_reasons_and_tracker(rng):
    obj, batch = _logistic_problem(rng)
    # max-iterations: cap at 2
    res = minimize_lbfgs(lambda w: obj.value_and_grad(w, batch), jnp.zeros(D),
                         SolverConfig(max_iters=2, tolerance=1e-16))
    assert res.convergence_reason() == ConvergenceReason.MAX_ITERATIONS
    assert int(res.iterations) == 2
    # tracker recorded initial + 2 states, monotone decreasing
    vals = np.asarray(res.tracker.values[: int(res.tracker.num_states)])
    assert len(vals) == 3 and vals[1] <= vals[0] and vals[2] <= vals[1]
    # stationary start: zero gradient at optimum of trivial problem
    res2 = minimize_lbfgs(lambda w: (jnp.vdot(w, w), 2 * w), jnp.zeros(D))
    assert res2.convergence_reason() == ConvergenceReason.GRADIENT_CONVERGED
    assert int(res2.iterations) == 0


def test_variances(rng):
    obj, batch = _logistic_problem(rng)
    res = jax.jit(make_solver(obj, OptimizerType.LBFGS))(jnp.zeros(D), batch)
    h = np.asarray(obj.hessian(res.w, batch))
    v_simple = compute_variances(obj, res.w, batch, VarianceComputationType.SIMPLE)
    np.testing.assert_allclose(v_simple, 1.0 / np.diagonal(h), rtol=1e-8)
    v_full = compute_variances(obj, res.w, batch, VarianceComputationType.FULL)
    np.testing.assert_allclose(v_full, np.diagonal(np.linalg.inv(h)), rtol=1e-7)
    assert compute_variances(obj, res.w, batch, VarianceComputationType.NONE) is None


def test_warm_start_fewer_iterations(rng):
    """Warm start (reference GameEstimator warm-start between configs) must
    converge in fewer iterations than cold start."""
    obj, batch = _logistic_problem(rng)
    solve = make_solver(obj, OptimizerType.LBFGS)
    cold = solve(jnp.zeros(D), batch)
    warm = solve(cold.w, batch)
    assert int(warm.iterations) <= 2
    np.testing.assert_allclose(warm.value, cold.value, rtol=1e-9)


# ---------------------------------------------------------------------------
# Legacy reg-path training API (reference ModelTraining.scala:106-228)
# ---------------------------------------------------------------------------

def test_train_glm_reg_path(rng):
    import scipy.optimize as sopt
    import scipy.special as spec

    from photon_ml_tpu.models.training import train_glm_reg_path
    from photon_ml_tpu.types import OptimizerType, TaskType

    n, d = 500, 6
    x = rng.normal(size=(n, d))
    w_true = rng.normal(size=d)
    y = (rng.random(n) < 1.0 / (1.0 + np.exp(-x @ w_true))).astype(float)

    lams = [0.1, 10.0, 1.0]
    path, trackers = train_glm_reg_path(x, y, TaskType.LOGISTIC_REGRESSION,
                                        lams, dtype=np.float64)

    # trained (and returned) in descending-λ order
    assert [lam for lam, _ in path] == [10.0, 1.0, 0.1]
    assert set(trackers) == {0.1, 1.0, 10.0}

    # each path point matches an independent scipy fit of the same objective
    for lam, model in path:
        def nll(w):
            z = x @ w
            return np.sum(np.logaddexp(0, z) - y * z) + 0.5 * lam * w @ w

        def grad(w):
            return x.T @ (spec.expit(x @ w) - y) + lam * w

        ref = sopt.minimize(nll, np.zeros(d), jac=grad, method="L-BFGS-B",
                            options={"maxiter": 200, "gtol": 1e-10})
        np.testing.assert_allclose(model.coefficients.means, ref.x,
                                   rtol=2e-4, atol=2e-4)

    # heavier regularization -> smaller coefficients
    norms = {lam: np.linalg.norm(m.coefficients.means) for lam, m in path}
    assert norms[10.0] < norms[1.0] < norms[0.1]


def test_train_glm_reg_path_warm_start_model(rng):
    from photon_ml_tpu.models.glm import Coefficients, GLMModel
    from photon_ml_tpu.models.training import train_glm_reg_path
    from photon_ml_tpu.types import TaskType

    n, d = 200, 4
    x = rng.normal(size=(n, d))
    y = (rng.random(n) < 0.5).astype(float)

    warm = {5.0: GLMModel(Coefficients(means=np.full(d, 0.3)),
                          TaskType.LOGISTIC_REGRESSION)}
    path, _ = train_glm_reg_path(x, y, TaskType.LOGISTIC_REGRESSION, [1.0],
                                 warm_start_models=warm, dtype=np.float64)
    path0, _ = train_glm_reg_path(x, y, TaskType.LOGISTIC_REGRESSION, [1.0],
                                  dtype=np.float64)
    # both converge to the same optimum; warm start just changes the route
    np.testing.assert_allclose(path[0][1].coefficients.means,
                               path0[0][1].coefficients.means, atol=1e-4)


def test_summarize_solver_results(rng):
    """Reference RandomEffectOptimizationTracker summary: reason counts +
    iteration/value stats over many (vmapped) solves, masked lanes excluded."""
    import jax.numpy as jnp

    from photon_ml_tpu.opt.types import SolverResult, summarize_solver_results
    from photon_ml_tpu.types import ConvergenceReason

    batched = SolverResult(
        w=jnp.zeros((4, 3)),
        value=jnp.asarray([1.0, 2.0, 3.0, 99.0]),
        grad_norm=jnp.zeros(4),
        iterations=jnp.asarray([5, 7, 9, 100], jnp.int32),
        reason=jnp.asarray([ConvergenceReason.GRADIENT_CONVERGED,
                            ConvergenceReason.GRADIENT_CONVERGED,
                            ConvergenceReason.MAX_ITERATIONS,
                            ConvergenceReason.MAX_ITERATIONS], jnp.int32),
    )
    # last lane is padding -> excluded
    s = summarize_solver_results([batched],
                                 valid_masks=[np.asarray([1, 1, 1, 0], bool)])
    assert s["count"] == 3
    assert s["convergence_reasons"] == {"GRADIENT_CONVERGED": 2,
                                        "MAX_ITERATIONS": 1}
    assert s["iterations"]["max"] == 9
    np.testing.assert_allclose(s["iterations"]["mean"], 7.0)
    np.testing.assert_allclose(s["final_value"]["mean"], 2.0)

    assert summarize_solver_results([])["count"] == 0


def test_re_coordinate_tracker_summary(rng):
    from photon_ml_tpu.core.regularization import Regularization
    from photon_ml_tpu.game import GameData, RandomEffectConfig
    from photon_ml_tpu.game.coordinate import build_coordinate
    from photon_ml_tpu.opt.types import SolverConfig
    from photon_ml_tpu.types import TaskType

    n_users, per = 7, 30
    n = n_users * per
    x = rng.normal(size=(n, 3))
    y = (rng.random(n) < 0.5).astype(float)
    uids = np.repeat(np.arange(n_users), per)
    data = GameData(y=y, features={"u": x}, id_tags={"uid": uids})
    coord = build_coordinate(
        "re", data,
        RandomEffectConfig(random_effect_type="uid", feature_shard="u",
                           solver=SolverConfig(max_iters=50),
                           reg=Regularization(l2=1.0)),
        TaskType.LOGISTIC_REGRESSION)
    _, trackers = coord.update(np.zeros(n))
    s = coord.tracker_summary(trackers)
    assert s["count"] == n_users  # padded lanes excluded
    assert sum(s["convergence_reasons"].values()) == n_users


def test_select_best_glm(rng):
    """Reference ModelSelection.scala: best λ on validation by the
    task-default metric (AUC for classifiers)."""
    from photon_ml_tpu.models.training import select_best_glm, train_glm_reg_path
    from photon_ml_tpu.types import TaskType

    x = rng.normal(size=(600, 5))
    w = rng.normal(size=5) * 2
    y = (rng.random(600) < 1.0 / (1.0 + np.exp(-x @ w))).astype(float)
    path, _ = train_glm_reg_path(x[:400], y[:400], TaskType.LOGISTIC_REGRESSION,
                                 [0.01, 1.0, 1000.0], dtype=np.float64)
    lam, model = select_best_glm(path, x[400:], y[400:])
    assert lam != 1000.0  # the crushed model can't win on AUC
    # metric override: logistic loss picks a (possibly different) minimum
    lam2, _ = select_best_glm(path, x[400:], y[400:], metric="logistic_loss")
    assert lam2 in (0.01, 1.0)
    with pytest.raises(ValueError):
        select_best_glm([], x, y)


def test_f32_plateau_exits_without_thrashing():
    """Regression for the working-precision plateau pathology
    (opt/linesearch.py approximate-Wolfe slack + opt/types.PLATEAU_ULPS):
    when tolerance*|f0| sits BELOW one ulp of f (a large constant offset
    makes ulp(f) huge), the solver must still exit via the value-based
    reasons in a handful of iterations — before the fix it burned
    max_iters x max_linesearch objective passes failing exact-Armijo at
    the rounding floor."""
    import jax.numpy as jnp

    from photon_ml_tpu.opt.lbfgs import minimize_lbfgs
    from photon_ml_tpu.opt.types import SolverConfig
    from photon_ml_tpu.types import ConvergenceReason

    big = jnp.float32(1e8)  # ulp(1e8) = 8.0 in f32

    def vg(w):
        f = big + 0.5 * jnp.sum((w - 1.0) ** 2)
        return f.astype(jnp.float32), (w - 1.0).astype(jnp.float32)

    w0 = jnp.zeros(4, jnp.float32)
    # tolerance*|f0| = 1e-9 * 1e8 = 0.1 << ulp(f) = 8 -> the floor must act
    res = minimize_lbfgs(vg, w0, SolverConfig(max_iters=50, tolerance=1e-9,
                                              max_linesearch=25))
    # the solve must exit via the VALUE-based reasons in a couple of steps;
    # before the fix the exact-Armijo test failed every trial at the
    # rounding floor and the exit reason was OBJECTIVE_NOT_IMPROVING after
    # a full max_linesearch of wasted evaluations
    assert int(res.reason) in (int(ConvergenceReason.FUNCTION_VALUES_CONVERGED),
                               int(ConvergenceReason.GRADIENT_CONVERGED)), \
        int(res.reason)
    assert int(res.iterations) <= 5, int(res.iterations)
    # NOTE deliberately no optimum assertion: at this offset the WHOLE
    # remaining descent (<= 2.0) sits below one ulp of f (8.0) — the
    # objective cannot resolve it, and stopping promptly is the point


class TestNewtonSoa:
    """The narrow-lane structure-of-arrays Newton solver (opt/newton_soa.py)
    must reach the SAME optimum as the vmapped generic path — it replaces
    it on the flagship GLMix random-effect shapes (dense, d<=16, smooth
    l2), so parity here is what licenses the swap."""

    def _bucket(self, rng, L=7, cap=12, d=5, loss_name="logistic"):
        import numpy as np

        x = rng.normal(size=(L, cap, d)).astype(np.float64)
        off = (rng.normal(size=(L, cap)) * 0.2).astype(np.float64)
        wt = (rng.random(size=(L, cap)) + 0.5).astype(np.float64)
        wt[:, cap - 3:] = 0.0          # padded rows
        x[:, cap - 3:, :] = 0.0
        off[:, cap - 3:] = 0.0
        wt[L - 1] = 0.0                # an entirely-padded lane
        x[L - 1] = 0.0
        logits = np.einsum("lcd,d->lc", x, rng.normal(size=d))
        if loss_name == "poisson":
            y = rng.poisson(np.exp(np.clip(logits * 0.3, -3, 3)))
        elif loss_name == "squared":
            y = logits + rng.normal(size=logits.shape) * 0.1
        else:
            y = (rng.random(size=logits.shape) < 1 / (1 + np.exp(-logits)))
        y = np.where(wt > 0, y, 0.0).astype(np.float64)
        l2 = np.where(np.arange(L) % 2 == 0, 0.5, 2.0).astype(np.float64)
        return x, y, off, wt, l2

    @pytest.mark.parametrize("loss_name", ["logistic", "squared", "poisson"])
    def test_matches_vmapped_lbfgs(self, rng, loss_name):
        import numpy as np

        from photon_ml_tpu.core.batch import DenseBatch
        from photon_ml_tpu.core.losses import loss_by_name
        from photon_ml_tpu.core.objective import GLMObjective
        from photon_ml_tpu.core.regularization import Regularization
        from photon_ml_tpu.opt.newton_soa import solve_newton_soa
        from photon_ml_tpu.opt.solve import make_solver

        x, y, off, wt, l2 = self._bucket(rng, loss_name=loss_name)
        L, cap, d = x.shape
        loss = loss_by_name(loss_name)
        cfg = SolverConfig(max_iters=200, tolerance=1e-10)

        solve = make_solver(GLMObjective(loss=loss), config=cfg)

        def one(lam, xx, yy, oo, ww):
            return solve(jnp.zeros(d, jnp.float64),
                         DenseBatch(x=xx, y=yy, offset=oo, weight=ww),
                         objective=GLMObjective(
                             loss=loss, reg=Regularization(l2=lam)))

        res_v = jax.vmap(one)(jnp.asarray(l2), jnp.asarray(x),
                              jnp.asarray(y), jnp.asarray(off),
                              jnp.asarray(wt))

        res_s = solve_newton_soa(
            loss, jnp.zeros((d, L), jnp.float64),
            jnp.asarray(x.transpose(1, 2, 0)), jnp.asarray(y.T),
            jnp.asarray(off.T), jnp.asarray(wt.T), jnp.asarray(l2), cfg)

        # same optimum to SOLVER tolerance: the SoA side lands at machine-
        # precision gradients (verified vs scipy in development); the vmapped
        # L-BFGS side may exit a few ulps earlier via its value-plateau
        # check, so the band is solver-scale, not machine-scale
        np.testing.assert_allclose(np.asarray(res_s.w.T),
                                   np.asarray(res_v.w),
                                   rtol=1e-3, atol=2e-4)
        # the weightless lane's optimum is exactly 0 under pure l2
        np.testing.assert_allclose(np.asarray(res_s.w.T)[L - 1], 0.0,
                                   atol=1e-12)
        assert int(jnp.max(res_s.iterations)) <= 25  # Newton, not LBFGS

    def test_cholesky_solve_matches_numpy(self, rng):
        import numpy as np

        from photon_ml_tpu.opt.newton_soa import _cholesky_solve_soa

        L, d = 11, 6
        a = rng.normal(size=(L, d, d))
        H = np.einsum("lij,lkj->lik", a, a) + np.eye(d) * 0.1
        g = rng.normal(size=(L, d))
        hh = [[jnp.asarray(H[:, i, j]) for j in range(d)] for i in range(d)]
        x = _cholesky_solve_soa(hh, jnp.asarray(g.T),
                                jnp.asarray(1e-300))
        ref = np.stack([np.linalg.solve(H[i], g[i]) for i in range(L)])
        np.testing.assert_allclose(np.asarray(x.T), ref, rtol=1e-8,
                                   atol=1e-10)

    def test_line_search_failure_keeps_iterate(self):
        """A non-finite Newton step (Hessian overflow -> NaN Cholesky) must
        not poison the lane: the fully rejected line search KEEPS the
        iterate (the pre-fix code computed w - 0*NaN = NaN) and reports
        OBJECTIVE_NOT_IMPROVING like the generic solvers, while healthy
        lanes in the same bucket still solve."""
        import numpy as np

        from photon_ml_tpu.core.losses import loss_by_name
        from photon_ml_tpu.opt.newton_soa import solve_newton_soa
        from photon_ml_tpu.types import ConvergenceReason

        L, cap, d = 2, 4, 3
        x = np.zeros((cap, d, L))
        x[:, :, 0] = 1e160          # H entries overflow -> inf/inf = NaN
        rng = np.random.default_rng(3)
        x[:, :, 1] = rng.normal(size=(cap, d))
        y = np.zeros((cap, L))
        off = np.zeros((cap, L))
        wt = np.ones((cap, L))
        l2 = np.full(L, 0.5)
        res = solve_newton_soa(
            loss_by_name("poisson"), jnp.zeros((d, L), jnp.float64),
            jnp.asarray(x), jnp.asarray(y), jnp.asarray(off),
            jnp.asarray(wt), jnp.asarray(l2),
            SolverConfig(max_iters=50, tolerance=1e-9))
        w = np.asarray(res.w)
        assert np.isfinite(w).all(), w
        np.testing.assert_array_equal(w[:, 0], 0.0)   # iterate preserved
        assert int(res.reason[0]) == int(
            ConvergenceReason.OBJECTIVE_NOT_IMPROVING)
        assert int(res.reason[1]) != int(
            ConvergenceReason.OBJECTIVE_NOT_IMPROVING)
        assert np.abs(w[:, 1]).max() > 0               # healthy lane solved
