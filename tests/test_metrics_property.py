"""Property tests for evaluation metrics vs brute-force references.

auc_roc backs every bench quality gate and every validation-driven model
selection, so it is checked here against the O(n^2) pairwise definition
(P[score_pos > score_neg] + 0.5 P[tie], weighted) on random score/label/
weight draws, including heavy ties.  rmse against the closed form.
"""

import os
import sys

import numpy as np
import pytest

pytest.importorskip("hypothesis")  # not in the image; skip, don't error at collection
from hypothesis import assume, given, settings
from hypothesis import strategies as st

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax.numpy as jnp  # noqa: E402

from photon_ml_tpu.evaluation import metrics  # noqa: E402

# small score alphabet -> dense ties, the hard case for rank-based AUC
_scores = st.lists(st.sampled_from([-1.0, -0.5, 0.0, 0.25, 0.5, 1.0]),
                   min_size=2, max_size=40)


def _pairwise_auc(s, y, w):
    """O(n^2) weighted pairwise AUC: sum over (pos, neg) pairs of
    w_p*w_n * (1[s_p > s_n] + 0.5*1[s_p == s_n]) / total pair weight."""
    num = den = 0.0
    for i in range(len(s)):
        if y[i] != 1:
            continue
        for j in range(len(s)):
            if y[j] != 0:
                continue
            pw = w[i] * w[j]
            den += pw
            if s[i] > s[j]:
                num += pw
            elif s[i] == s[j]:
                num += 0.5 * pw
    return num / den if den else float("nan")


@settings(max_examples=60, deadline=None)
@given(data=st.data(), scores=_scores)
def test_auc_matches_pairwise_definition(data, scores):
    n = len(scores)
    labels = data.draw(st.lists(st.sampled_from([0.0, 1.0]),
                                min_size=n, max_size=n))
    assume(0.0 in labels and 1.0 in labels)
    weights = data.draw(st.lists(st.sampled_from([0.5, 1.0, 2.0]),
                                 min_size=n, max_size=n))
    got = float(metrics.auc_roc(jnp.asarray(scores), jnp.asarray(labels),
                                jnp.asarray(weights)))
    want = _pairwise_auc(scores, labels, weights)
    np.testing.assert_allclose(got, want, rtol=1e-9, atol=1e-9)


@settings(max_examples=60, deadline=None)
@given(data=st.data(), scores=_scores)
def test_rmse_closed_form(data, scores):
    n = len(scores)
    labels = data.draw(st.lists(st.floats(-2, 2), min_size=n, max_size=n))
    weights = data.draw(st.lists(st.sampled_from([0.5, 1.0, 2.0]),
                                 min_size=n, max_size=n))
    got = float(metrics.rmse(jnp.asarray(scores), jnp.asarray(labels),
                             jnp.asarray(weights)))
    s, y, w = map(np.asarray, (scores, labels, weights))
    want = float(np.sqrt(np.sum(w * (s - y) ** 2) / np.sum(w)))
    np.testing.assert_allclose(got, want, rtol=1e-9)


@settings(max_examples=40, deadline=None)
@given(data=st.data(), scores=_scores)
def test_auc_invariant_under_monotone_transform(data, scores):
    """AUC is a rank statistic: any strictly increasing transform of the
    scores leaves it unchanged (the reference's evaluators share this
    contract — model selection must not depend on score calibration)."""
    n = len(scores)
    labels = data.draw(st.lists(st.sampled_from([0.0, 1.0]),
                                min_size=n, max_size=n))
    assume(0.0 in labels and 1.0 in labels)
    w = jnp.ones(n)
    s = jnp.asarray(scores)
    a1 = float(metrics.auc_roc(s, jnp.asarray(labels), w))
    a2 = float(metrics.auc_roc(jnp.tanh(s) * 3 + 7, jnp.asarray(labels), w))
    np.testing.assert_allclose(a1, a2, rtol=1e-9, atol=1e-9)
