"""photonpulse tests (ISSUE 15): cross-process tracing, merge, flight.

The contracts under test:
  - context: mint/wire round-trip, *strictly tolerant* decode (every
    malformed wire form degrades to None — a bad trace header must never
    fail a request), thread-local binding stamping ``trace=``/``origin=``
    attrs on spans and instants, and the bounded delta-identity map.
  - clock: the four-timestamp NTP-style estimate recovers a known epoch
    offset, ``observe_exchange`` keeps the lowest-rtt sample, and
    ``pulse.configure`` exposes the offset table through every Chrome
    export's ``otherData``.
  - flight: dumps are self-contained (reason/detail/trace), rate-limited,
    byte-bounded oldest-first, and triggered by the real degradation
    paths — a HealthState ok->failed transition (driven end-to-end by a
    chaos fault on the delta log) and the admission shed latch — then
    retrievable via ``{"cmd": "flight"}`` on the stdio serve wire.
  - merge: known clock offsets shift events onto the reference timeline,
    reference auto-detection picks the label peers measured against, pids
    are re-numbered collision-free, and ``spans_by_trace`` buckets batched
    spans (``traces=[...]``) under every trace they served.
  - exemplars: latency histograms attach the bound trace id per bucket
    and render OpenMetrics-style exemplar suffixes ONLY while enabled —
    the Prometheus golden elsewhere stays byte-stable.
  - propagation: ``request_from_json`` adopts/rejects wire ``"tp"``,
    replication frames carry ``"tp"`` beside (never inside) the CRC'd
    payload, and the network frontend mints at admission / adopts from
    the wire with garbage degrading to untraced.
  - the pod-slice e2e: an in-process owner publishing under a minted
    context, a REAL ``serve --subscribe`` replica subprocess, and a
    frontend leg merged by ``tools/tracemerge.py`` into one timeline where
    the owner's publish precedes the replica's store-visible instant under
    the same trace id and the frontend request span encloses its flush.
"""

import io
import json
import os
import select
import socket
import subprocess
import sys
import time

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from photon_ml_tpu import obs
from photon_ml_tpu.obs import pulse
from photon_ml_tpu.obs.pulse import clock as pclock
from photon_ml_tpu.obs.pulse import context as pctx
from photon_ml_tpu.obs.pulse.flight import (FlightRecorder, flight_dump,
                                            set_flight)
from photon_ml_tpu.obs.pulse.merge import merge_traces, spans_by_trace
from photon_ml_tpu.obs.registry import MetricsRegistry, enable_exemplars
from photon_ml_tpu.obs.trace import (Tracer, set_export_meta_provider,
                                     set_process_label)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def tracer():
    """A fresh enabled tracer installed as the process default; restored
    (and tracing re-disabled) afterwards so tests never leak spans."""
    t = Tracer(capacity=4096, enabled=True)
    prev = obs.set_tracer(t)
    try:
        yield t
    finally:
        obs.set_tracer(prev)


@pytest.fixture(autouse=True)
def _pulse_clean():
    """photonpulse keeps process-global state (clock table, delta map,
    flight recorder, process label, export hook, exemplar flag) — every
    test starts and ends with all of it cleared."""
    yield
    pclock.reset()
    pctx.clear_delta_ctx()
    set_flight(None)
    set_process_label(None)
    set_export_meta_provider(None)
    enable_exemplars(False)


def _wait(pred, timeout=30.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.01)
    raise AssertionError(f"timed out waiting for {msg}")


# ---------------------------------------------------------------------------
# context
# ---------------------------------------------------------------------------
class TestContext:
    def test_mint_shape_and_uniqueness(self):
        seen = set()
        for _ in range(64):
            tid, origin = pctx.mint()
            assert len(tid) == 16 and set(tid) <= set("0123456789abcdef")
            assert len(origin) == 8 and set(origin) <= set("0123456789abcdef")
            seen.add(tid)
        assert len(seen) == 64  # 64-bit ids: collisions would be a bug

    def test_wire_round_trip(self):
        ctx = pctx.mint()
        wire = pctx.to_wire(ctx)
        assert wire == f"{ctx[0]}/{ctx[1]}"
        assert pctx.from_wire(wire) == ctx

    def test_from_wire_garbage_degrades_to_none(self):
        good = pctx.to_wire(pctx.mint())
        for bad in (None, 7, 1.5, b"0123456789abcdef/01234567",
                    "", "garbage", good[:-1], good + "0",
                    good.upper(),                       # hex is lowercase
                    "0123456789abcdef_01234567",        # right length, no /
                    "0123456789abcde/012345678",        # 15/9 split
                    "0123456789abcdeg/01234567",        # non-hex trace id
                    "0123456789abcdef/0123456z",        # non-hex origin
                    ["0123456789abcdef", "01234567"]):
            assert pctx.from_wire(bad) is None, bad

    def test_forwarded_keeps_trace_id_fresh_origin(self):
        ctx = pctx.mint()
        fwd = pctx.forwarded(ctx)
        assert fwd[0] == ctx[0]
        assert len(fwd[1]) == 8 and fwd[1] != ctx[1]
        assert pctx.from_wire(pctx.to_wire(fwd)) == fwd

    def test_bind_stamps_span_and_instant_attrs(self, tracer):
        ctx = pctx.mint()
        with pctx.bind(ctx):
            with obs.span("work", k=1):
                obs.instant("tick")
            inner = pctx.mint()
            with pctx.bind(inner):       # re-entrant: innermost wins
                obs.instant("nested")
            obs.instant("restored")      # outer binding restored
            with pctx.bind(None):        # explicit unbind
                obs.instant("unbound")
        obs.instant("outside")
        recs = {r["name"]: r for r in tracer.records()}
        assert recs["work"]["attrs"]["trace"] == ctx[0]
        assert recs["work"]["attrs"]["origin"] == ctx[1]
        assert recs["work"]["attrs"]["k"] == 1
        assert recs["tick"]["attrs"]["trace"] == ctx[0]
        assert recs["nested"]["attrs"]["trace"] == inner[0]
        assert recs["restored"]["attrs"]["trace"] == ctx[0]
        assert "trace" not in recs["unbound"]["attrs"]
        assert "trace" not in recs["outside"]["attrs"]
        assert pctx.current() is None

    def test_delta_map_lookup_and_bounded_eviction(self):
        ctx = pctx.mint()
        pctx.note_delta((1, 1), ctx)
        pctx.note_delta((1, 2), None)     # untraced publish: no entry
        assert pctx.delta_ctx((1, 1)) == ctx
        assert pctx.delta_ctx((1, 2)) is None
        assert pctx.delta_ctx((9, 9)) is None
        for v in range(pctx._DELTA_MAP_CAP + 8):
            pctx.note_delta((2, v), ctx)
        assert pctx.delta_ctx((1, 1)) is None      # oldest evicted
        assert pctx.delta_ctx((2, 0)) is None
        assert pctx.delta_ctx((2, pctx._DELTA_MAP_CAP)) == ctx


# ---------------------------------------------------------------------------
# clock
# ---------------------------------------------------------------------------
class TestClock:
    def test_estimate_recovers_known_offset(self):
        # server's epoch runs 7777ns ahead; symmetric 400ns network legs
        skew, leg, proc = 7777, 400, 50
        t0 = 1_000_000
        t1 = t0 + leg + skew
        t2 = t1 + proc
        t3 = t0 + leg + proc + leg
        offset, rtt = pclock.estimate(t0, t1, t2, t3)
        assert offset == skew
        assert rtt == 2 * leg

    def test_observe_exchange_keeps_lowest_rtt(self):
        pclock.observe_exchange("owner", 0, 1100, 1150, 300)   # rtt 250
        assert pclock.offsets()["owner"]["rtt_ns"] == 250
        pclock.observe_exchange("owner", 0, 5000, 5100, 1000)  # rtt 900
        assert pclock.offsets()["owner"]["rtt_ns"] == 250      # noisier: kept
        pclock.observe_exchange("owner", 0, 1050, 1060, 120)   # rtt 110
        est = pclock.offsets()["owner"]
        assert est["rtt_ns"] == 110
        assert est["offset_ns"] == ((1050 - 0) + (1060 - 120)) // 2

    def test_configure_exposes_offsets_in_export(self, tracer):
        pulse.configure("replica")
        pclock.set_offset("owner", 123_456, rtt_ns=789)
        with obs.span("x"):
            pass
        other = tracer.chrome_trace()["otherData"]
        assert other["process_label"] == "replica"
        assert other["clock"] == {"owner": {"offset_ns": 123_456,
                                            "rtt_ns": 789}}


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------
class TestFlight:
    def test_dump_payload_and_snapshot(self, tmp_path, tracer):
        with obs.span("before.incident", k=1):
            pass
        rec = FlightRecorder(str(tmp_path / "spool"), min_interval_s=0.0)
        path = rec.dump("health_degraded", check="delta_log", detail="io")
        assert path is not None and os.path.exists(path)
        payload = json.load(open(path))
        assert payload["reason"] == "health_degraded"
        assert payload["detail"] == {"check": "delta_log", "detail": "io"}
        names = {e["name"] for e in payload["trace"]["traceEvents"]}
        assert "before.incident" in names  # the ring survived the incident
        snap = rec.snapshot()
        assert snap["spool_dir"] == str(tmp_path / "spool")
        assert [d["reason"] for d in snap["dumps"]] == ["health_degraded"]
        assert snap["latest"]["reason"] == "health_degraded"

    def test_rate_limit_coalesces_trigger_storms(self, tmp_path, tracer):
        rec = FlightRecorder(str(tmp_path / "spool"), min_interval_s=60.0)
        assert rec.dump("first") is not None
        assert rec.dump("second") is None       # within the interval
        assert len(rec.index()) == 1

    def test_byte_bound_evicts_oldest_first(self, tmp_path, tracer):
        rec = FlightRecorder(str(tmp_path / "spool"), min_interval_s=0.0)
        paths = [rec.dump(f"r{i}") for i in range(3)]
        size = os.path.getsize(paths[-1])
        rec.max_bytes = int(size * 2.5)         # room for two dumps
        for i in range(3, 6):
            assert rec.dump(f"r{i}") is not None
        reasons = [d["reason"] for d in rec.index()]
        assert reasons[-1] == "r5"              # newest always survives
        assert "r0" not in reasons and "r1" not in reasons
        total = sum(d["bytes"] for d in rec.index())
        assert total <= rec.max_bytes

    def test_module_trigger_is_one_none_check(self, tmp_path, tracer):
        assert flight_dump("nothing_installed") is None
        rec = FlightRecorder(str(tmp_path / "spool"), min_interval_s=0.0)
        set_flight(rec)
        assert flight_dump("installed", k=1) is not None

    def test_health_transition_triggers_dump(self, tmp_path, tracer):
        from photon_ml_tpu.chaos.health import HealthState

        set_flight(FlightRecorder(str(tmp_path / "spool"),
                                  min_interval_s=0.0))
        hs = HealthState()
        hs.set_condition("disk", True, "fine")
        rec = pulse.get_flight()
        assert rec.index() == []                # ok -> ok: no dump
        hs.set_condition("disk", False, "enospc")
        assert len(rec.index()) == 1            # the ok -> failed edge
        hs.set_condition("disk", False, "still enospc")
        assert len(rec.index()) == 1            # failed -> failed: no edge
        hs.set_condition("disk", True, "healed")
        hs.set_condition("disk", False, "again")
        assert len(rec.index()) == 2            # a fresh edge dumps again
        latest = rec.latest()
        assert latest["reason"] == "health_degraded"
        assert latest["detail"]["check"] == "disk"

    def test_admission_shed_latch_triggers_dump(self, tmp_path, tracer):
        from photon_ml_tpu.serving.frontend import (AdmissionConfig,
                                                    AdmissionController)

        set_flight(FlightRecorder(str(tmp_path / "spool"),
                                  min_interval_s=0.0))
        ac = AdmissionController(AdmissionConfig(budget_s=0.010,
                                                 resume_fraction=0.5))
        assert ac.decide(0.005).admitted
        rec = pulse.get_flight()
        assert rec.index() == []
        assert not ac.decide(0.050).admitted    # latch engages
        assert [d["reason"] for d in rec.index()] == ["admission_shed"]
        assert not ac.decide(0.040).admitted    # still latched: no new dump
        assert len(rec.index()) == 1

    def test_chaos_delta_log_fault_dumps_flight(self, tmp_path, tracer):
        """The acceptance chain: injected delta-log fault -> append fails
        -> health check transitions -> flight dump lands on disk."""
        from photon_ml_tpu.chaos import (FaultInjector, delta_log_check,
                                         set_injector)
        from photon_ml_tpu.chaos.health import HealthState
        from photon_ml_tpu.online.delta_log import DeltaLog, DeltaRecord

        set_flight(FlightRecorder(str(tmp_path / "spool"),
                                  min_interval_s=0.0))
        log = DeltaLog(str(tmp_path / "log"), fsync="never")
        hs = HealthState()
        hs.add_check("delta_log", delta_log_check(log))
        ready, _ = hs.readyz()
        assert ready
        inj = FaultInjector()
        inj.arm("delta_log.append", kind="enospc")
        prev = set_injector(inj)
        try:
            with pctx.bind(pctx.mint()):
                with pytest.raises(OSError):
                    log.append(DeltaRecord(generation=1, delta_version=1,
                                           cid="user", entity="u1",
                                           row=(1.0, 2.0)))
        finally:
            set_injector(prev)
            log.close()
        ready, checks = hs.readyz()
        assert not ready and not checks["delta_log"]["ok"]
        rec = pulse.get_flight()
        latest = rec.latest()
        assert latest["reason"] == "health_degraded"
        assert latest["detail"]["check"] == "delta_log"
        assert "write error" in latest["detail"]["detail"]

    def test_serve_stream_flight_cmd(self, tmp_path, tracer):
        """``{"cmd": "flight"}`` on the stdio wire returns the snapshot;
        without ``--flight-dir`` it explains how to get one."""
        import contextlib

        from test_serving import _train
        from photon_ml_tpu.cli import serve as serve_cli

        model_dir = _train(tmp_path, seed=7)
        spool = str(tmp_path / "spool")
        FlightRecorder(spool, min_interval_s=0.0).dump("health_degraded",
                                                       check="delta_log")
        req_file = str(tmp_path / "reqs.jsonl")
        with open(req_file, "w") as f:
            f.write(json.dumps({"cmd": "flight"}) + "\n")

        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            rc = serve_cli.run(["--model-dir", model_dir, "--requests",
                                req_file, "--no-warm",
                                "--flight-dir", spool])
        assert rc == 0
        set_flight(None)  # run() installed a recorder; drop it
        reply = json.loads(buf.getvalue().splitlines()[0])
        assert reply["flight"]["spool_dir"] == spool
        assert [d["reason"] for d in reply["flight"]["dumps"]] == \
            ["health_degraded"]
        assert reply["flight"]["latest"]["detail"] == {"check": "delta_log"}

        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            rc = serve_cli.run(["--model-dir", model_dir, "--requests",
                                req_file, "--no-warm"])
        assert rc == 0
        reply = json.loads(buf.getvalue().splitlines()[0])
        assert "--flight-dir" in reply["error"]


# ---------------------------------------------------------------------------
# merge
# ---------------------------------------------------------------------------
def _mk_trace(label, events, clock=None, pid=4242):
    other = {"process_label": label, "pid": pid}
    if clock is not None:
        other["clock"] = clock
    return {"traceEvents": list(events), "displayTimeUnit": "ns",
            "otherData": other}


def _ev(name, ts, pid=4242, tid=1, trace=None, traces=None, ph="X", dur=10):
    args = {}
    if trace is not None:
        args["trace"] = trace
    if traces is not None:
        args["traces"] = traces
    ev = {"name": name, "ph": ph, "ts": ts, "pid": pid, "tid": tid,
          "args": args}
    if ph == "X":
        ev["dur"] = dur
    return ev


class TestMerge:
    def test_alignment_shifts_onto_reference_clock(self):
        tid = "ab" * 8
        owner = _mk_trace("owner", [_ev("online.publish", 1000, trace=tid)])
        # replica measured: owner's clock = replica's clock + 5ms
        replica = _mk_trace(
            "replica", [_ev("online.store_visible", 100, trace=tid, ph="i")],
            clock={"owner": {"offset_ns": 5_000_000, "rtt_ns": 900}})
        merged = merge_traces([owner, replica])
        other = merged["otherData"]
        assert other["reference"] == "owner"   # auto-detected root
        assert other["offsets_ns"] == {"owner": 0, "replica": 5_000_000}
        by_name = {e["name"]: e for e in merged["traceEvents"]
                   if e.get("ph") != "M"}
        assert by_name["online.publish"]["ts"] == 1000
        assert by_name["online.store_visible"]["ts"] == 100 + 5000.0
        assert other["trace_ids"] == {tid: 2}

    def test_reference_override_inverts_shift(self):
        owner = _mk_trace("owner", [_ev("a", 1000)])
        replica = _mk_trace(
            "replica", [_ev("b", 100)],
            clock={"owner": {"offset_ns": 5_000_000, "rtt_ns": 900}})
        merged = merge_traces([owner, replica], reference="replica")
        other = merged["otherData"]
        assert other["reference"] == "replica"
        assert other["offsets_ns"] == {"owner": -5_000_000, "replica": 0}

    def test_pid_renumber_and_process_metadata(self):
        # both processes exported the same OS pid (restart collision)
        t1 = _mk_trace("owner", [_ev("a", 10, pid=7)], pid=7)
        t2 = _mk_trace("replica", [_ev("b", 20, pid=7)], pid=7)
        merged = merge_traces([t1, t2])
        body = [e for e in merged["traceEvents"] if e.get("ph") != "M"]
        assert {e["pid"] for e in body} == {1, 2}
        meta = {e["pid"]: e["args"]["name"]
                for e in merged["traceEvents"]
                if e.get("ph") == "M" and e["name"] == "process_name"}
        assert meta == {1: "owner", 2: "replica"}
        assert merged["otherData"]["processes"] == {"1": "owner",
                                                    "2": "replica"}

    def test_unlinked_label_keeps_zero_shift(self):
        front = _mk_trace("frontend", [_ev("front.request", 50)])
        replica = _mk_trace(
            "replica", [_ev("b", 100)],
            clock={"owner": {"offset_ns": 5_000_000, "rtt_ns": 900}})
        owner = _mk_trace("owner", [_ev("a", 10)])
        merged = merge_traces([front, replica, owner])
        other = merged["otherData"]
        assert other["reference"] == "owner"
        assert other["offsets_ns"]["frontend"] == 0  # no path: unshifted

    def test_disconnected_graph_degrades_to_component_references(self):
        # two deployments merged after the fact: {owner, replica} pinged
        # each other, {edge-a, edge-b} pinged each other, no cross edges
        owner = _mk_trace("owner", [_ev("a", 10)])
        replica = _mk_trace(
            "replica", [_ev("b", 100)],
            clock={"owner": {"offset_ns": 5_000_000, "rtt_ns": 900}})
        edge_a = _mk_trace("edge-a", [_ev("c", 20)])
        edge_b = _mk_trace(
            "edge-b", [_ev("d", 200)],
            clock={"edge-a": {"offset_ns": -2_000_000, "rtt_ns": 800}})
        merged = merge_traces([owner, replica, edge_a, edge_b])
        other = merged["otherData"]
        assert other["reference"] == "owner"
        # the island got its OWN local reference, not a silent zero-shift
        refs = other["component_references"]
        assert refs["owner"] == "owner" and refs["replica"] == "owner"
        assert refs["edge-a"] == refs["edge-b"] == "edge-a"
        # within the island relative timing is still exact
        assert other["offsets_ns"]["edge-b"] \
            - other["offsets_ns"]["edge-a"] == -2_000_000
        warnings = other["clock_warnings"]
        assert len(warnings) == 1 and "disconnected" in warnings[0]
        assert "edge-a" in warnings[0] and "edge-b" in warnings[0]

    def test_connected_graph_has_no_clock_warnings(self):
        owner = _mk_trace("owner", [_ev("a", 10)])
        replica = _mk_trace(
            "replica", [_ev("b", 100)],
            clock={"owner": {"offset_ns": 5_000_000, "rtt_ns": 900}})
        merged = merge_traces([owner, replica])
        other = merged["otherData"]
        assert other["clock_warnings"] == []
        assert set(other["component_references"].values()) == {"owner"}

    def test_tracemerge_cli_warns_on_disconnect_even_quiet(
            self, tmp_path, capsys):
        from tools.tracemerge import run
        paths = []
        for label, clock in (("owner", None),
                             ("edge", {"nowhere": {"offset_ns": 1,
                                                   "rtt_ns": 1}})):
            # "edge" measured a peer that is not in the merge set: its
            # component is disconnected from the owner's
            p = tmp_path / f"{label}.json"
            p.write_text(json.dumps(_mk_trace(label, [_ev("x", 1)],
                                              clock=clock)))
            paths.append(str(p))
        out = tmp_path / "merged.json"
        assert run(paths + ["--out", str(out), "--quiet"]) == 0
        err = capsys.readouterr().err
        assert "warning" in err and "disconnected" in err
        merged = json.loads(out.read_text())
        assert merged["otherData"]["clock_warnings"]

    def test_events_sorted_metadata_first(self):
        t1 = _mk_trace("owner", [_ev("late", 500), _ev("early", 5)])
        t2 = _mk_trace("replica", [_ev("mid", 50)])
        merged = merge_traces([t1, t2])
        phases = [e.get("ph") for e in merged["traceEvents"]]
        first_body = phases.index("X")
        assert all(p == "M" for p in phases[:first_body])
        body_ts = [e["ts"] for e in merged["traceEvents"][first_body:]]
        assert body_ts == sorted(body_ts)

    def test_spans_by_trace_buckets_batched_spans(self):
        ta, tb = "aa" * 8, "bb" * 8
        merged = merge_traces([_mk_trace("owner", [
            _ev("front.request", 10, trace=ta),
            _ev("front.request", 12, trace=tb),
            _ev("serve.flush", 11, traces=[ta, tb]),
        ])])
        by = spans_by_trace(merged)
        assert set(by) == {ta, tb}
        assert [e["name"] for e in by[ta]] == ["front.request", "serve.flush"]
        assert [e["name"] for e in by[tb]] == ["serve.flush", "front.request"]


# ---------------------------------------------------------------------------
# histogram exemplars
# ---------------------------------------------------------------------------
class TestExemplars:
    def test_exemplar_rendered_only_while_enabled(self, tracer):
        reg = MetricsRegistry()
        ctx = pctx.mint()
        enable_exemplars(True)
        with pctx.bind(ctx):
            reg.observe("latency_seconds", 0.004, path="score")
        text = reg.to_prometheus()
        assert f'# {{trace_id="{ctx[0]}"}}' in text
        exemplar_lines = [l for l in text.splitlines() if "trace_id=" in l]
        assert exemplar_lines and all("_bucket" in l for l in exemplar_lines)
        # the flag gates RENDERING too: stored exemplars vanish when off,
        # so the golden Prometheus exposition elsewhere stays byte-stable
        enable_exemplars(False)
        assert "trace_id=" not in reg.to_prometheus()
        enable_exemplars(True)
        assert f'# {{trace_id="{ctx[0]}"}}' in reg.to_prometheus()

    def test_disabled_observe_records_no_exemplar(self, tracer):
        reg = MetricsRegistry()
        with pctx.bind(pctx.mint()):
            reg.observe("latency_seconds", 0.004, path="score")
        enable_exemplars(True)          # enabled AFTER the observation
        assert "trace_id=" not in reg.to_prometheus()

    def test_unbound_observe_records_no_exemplar(self, tracer):
        reg = MetricsRegistry()
        enable_exemplars(True)
        reg.observe("latency_seconds", 0.004, path="score")
        assert "trace_id=" not in reg.to_prometheus()

    def test_newest_sample_wins_per_bucket(self, tracer):
        reg = MetricsRegistry()
        enable_exemplars(True)
        a, b = pctx.mint(), pctx.mint()
        with pctx.bind(a):
            reg.observe("latency_seconds", 0.0050)
        with pctx.bind(b):
            reg.observe("latency_seconds", 0.0051)   # same 2^k bucket
        text = reg.to_prometheus()
        assert f'trace_id="{b[0]}"' in text
        assert f'trace_id="{a[0]}"' not in text


# ---------------------------------------------------------------------------
# OpenMetrics exposition
# ---------------------------------------------------------------------------
class TestOpenMetrics:
    def test_counter_total_suffix_and_eof_terminator(self, tracer):
        reg = MetricsRegistry()
        reg.inc("plain", 2)
        reg.inc("requests_total", 3, model="a")  # suffix already present
        reg.set_gauge("depth", 4.0)
        om = reg.to_openmetrics()
        assert om.endswith("# EOF\n")
        assert "# TYPE plain counter" in om and "plain_total 2" in om
        # the family name loses the _total suffix; the sample keeps it
        assert "# TYPE requests counter" in om
        assert 'requests_total{model="a"} 3' in om
        assert "# TYPE depth gauge" in om and "depth 4.0" in om

    def test_bucket_exemplars_render_without_the_render_gate(self, tracer):
        # the switch gates RECORDING; OpenMetrics exposes whatever was
        # recorded (an OpenMetrics scraper asked for the richer parse)
        reg = MetricsRegistry()
        ctx = pctx.mint()
        enable_exemplars(True)
        with pctx.bind(ctx):
            reg.observe("latency_seconds", 0.004, path="score")
        enable_exemplars(False)
        om = reg.to_openmetrics()
        exemplar_lines = [l for l in om.splitlines() if "trace_id=" in l]
        assert exemplar_lines and all("_bucket" in l for l in exemplar_lines)
        assert f'# {{trace_id="{ctx[0]}"}} 0.004' in om
        assert "latency_seconds_sum" in om and "latency_seconds_count" in om

    def test_histogram_without_exemplars_is_plain(self, tracer):
        reg = MetricsRegistry()
        reg.observe("latency_seconds", 0.004)
        om = reg.to_openmetrics()
        assert "trace_id=" not in om
        assert 'latency_seconds_bucket{le="+Inf"} 1' in om


# ---------------------------------------------------------------------------
# wire propagation units
# ---------------------------------------------------------------------------
class TestWirePropagation:
    def test_request_from_json_adopts_and_rejects_tp(self, tracer):
        from photon_ml_tpu.serving.batcher import request_from_json

        ctx = pctx.mint()
        req = request_from_json({"uid": 1, "features": [["f0", 1.0]],
                                 "tp": pctx.to_wire(ctx)})
        assert req.ctx == ctx
        req = request_from_json({"uid": 2, "features": [["f0", 1.0]],
                                 "tp": "torn-garbage"})
        assert req.ctx is None          # degrades, never raises

    def test_request_tp_skipped_when_tracing_off(self):
        from photon_ml_tpu.serving.batcher import request_from_json

        prev = obs.set_tracer(Tracer(capacity=16, enabled=False))
        try:
            req = request_from_json({"uid": 1, "features": [],
                                     "tp": pctx.to_wire(pctx.mint())})
            assert req.ctx is None      # one-boolean disabled path
        finally:
            obs.set_tracer(prev)

    def test_record_line_tp_rides_beside_payload(self):
        from photon_ml_tpu.online.delta_log import DeltaRecord
        from photon_ml_tpu.online.replication.wire import encode_record_line

        rec = DeltaRecord(generation=3, delta_version=9, cid="user",
                          entity="u1", row=(1.0, 2.0))
        bare = json.loads(encode_record_line(rec))
        ctx = pctx.mint()
        traced = json.loads(encode_record_line(rec, tp=pctx.to_wire(ctx)))
        # the replication invariant: tp must not perturb payload or CRC
        assert traced["p"] == bare["p"] and traced["crc"] == bare["crc"]
        assert "tp" not in bare
        assert pctx.from_wire(traced["tp"]) == ctx


# ---------------------------------------------------------------------------
# frontend propagation (in-process socket)
# ---------------------------------------------------------------------------
N_ENT = 12
D = 3
NAMES = [f"f{j}" for j in range(D)]


def _save_model_dir(path, seed=0):
    from photon_ml_tpu.data.index_map import IndexMap, feature_key
    from photon_ml_tpu.data.reader import EntityIndex
    from photon_ml_tpu.models.game import (FixedEffectModel, GameModel,
                                           RandomEffectModel)
    from photon_ml_tpu.models.glm import Coefficients
    from photon_ml_tpu.storage.model_io import save_game_model
    from photon_ml_tpu.types import TaskType

    rng = np.random.default_rng(seed)
    task = TaskType.LOGISTIC_REGRESSION
    model = GameModel(models={
        "fixed": FixedEffectModel(
            coefficients=Coefficients(means=rng.normal(size=D)),
            feature_shard="all", task=task),
        "user": RandomEffectModel(
            w_stack=rng.normal(size=(N_ENT, D)) * 0.5,
            slot_of={i: i for i in range(N_ENT)},
            random_effect_type="userId", feature_shard="all", task=task),
    })
    imap = IndexMap({feature_key(n): j for j, n in enumerate(NAMES)})
    eidx = EntityIndex()
    for i in range(N_ENT):
        eidx.get_or_add(f"user{i}")
    save_game_model(model, path, {"all": imap}, {"userId": eidx}, task=task)
    imap.save(os.path.join(path, "all.idx"))
    eidx.save(os.path.join(path, "userId.entities.json"))
    return path


def _wire_req(uid, user=0, tp=None):
    obj = {"uid": uid,
           "features": [[n, 0.25 * (j + 1)] for j, n in enumerate(NAMES)],
           "ids": {"userId": f"user{user}"}}
    if tp is not None:
        obj["tp"] = tp
    return obj


class _Client:
    """Blocking socket client speaking the JSON-lines wire protocol."""

    def __init__(self, port, timeout=60):
        self.sock = socket.create_connection(("127.0.0.1", port),
                                             timeout=timeout)
        self.f = self.sock.makefile("rw", encoding="utf-8", newline="\n")

    def ask(self, obj):
        self.f.write(json.dumps(obj) + "\n")
        self.f.flush()
        line = self.f.readline()
        assert line, "server closed the connection unexpectedly"
        return json.loads(line)

    def close(self):
        try:
            self.f.close()
        finally:
            self.sock.close()


def _engine(max_batch=8):
    from photon_ml_tpu.data.index_map import IndexMap, feature_key
    from photon_ml_tpu.data.reader import EntityIndex
    from photon_ml_tpu.models.game import (FixedEffectModel, GameModel,
                                           RandomEffectModel)
    from photon_ml_tpu.models.glm import Coefficients
    from photon_ml_tpu.serving.batcher import BucketedBatcher
    from photon_ml_tpu.serving.coefficient_store import (CoefficientStore,
                                                         StoreConfig)
    from photon_ml_tpu.serving.engine import ScoringEngine
    from photon_ml_tpu.serving.metrics import ServingMetrics
    from photon_ml_tpu.types import TaskType

    rng = np.random.default_rng(0)
    task = TaskType.LOGISTIC_REGRESSION
    model = GameModel(models={
        "fixed": FixedEffectModel(
            coefficients=Coefficients(means=rng.normal(size=D)),
            feature_shard="all", task=task),
        "user": RandomEffectModel(
            w_stack=rng.normal(size=(N_ENT, D)) * 0.5,
            slot_of={i: i for i in range(N_ENT)},
            random_effect_type="userId", feature_shard="all", task=task),
    })
    imap = IndexMap({feature_key(n): j for j, n in enumerate(NAMES)})
    eidx = EntityIndex()
    for i in range(N_ENT):
        eidx.get_or_add(f"user{i}")
    metrics = ServingMetrics()
    store = CoefficientStore.from_model(
        model, task, {"userId": eidx}, {"all": imap},
        config=StoreConfig(device_capacity=None), version="synthetic",
        metrics=metrics)
    eng = ScoringEngine(store, BucketedBatcher(max_batch), metrics=metrics)
    eng.warm()
    return eng


class TestFrontendPropagation:
    def test_mint_adopt_and_garbage_degrade(self, tracer):
        from photon_ml_tpu.serving.frontend import (AdmissionConfig,
                                                    FrontendConfig,
                                                    ThreadedFrontend)

        pulse.configure("frontend")
        front = ThreadedFrontend(
            _engine(), config=FrontendConfig(
                admission=AdmissionConfig(budget_s=30.0),
                batcher_deadline_s=0.002)).start()
        supplied = pctx.mint()
        try:
            c = _Client(front.port)
            try:
                r0 = c.ask(_wire_req(0))                       # no tp: mint
                r1 = c.ask(_wire_req(1, tp=pctx.to_wire(supplied)))
                r2 = c.ask(_wire_req(2, tp="complete/garbage!!!!"))
                assert all("score" in r for r in (r0, r1, r2))
            finally:
                c.close()
        finally:
            front.stop()
        recs = tracer.records()
        front_spans = {r["attrs"]["uid"]: r for r in recs
                       if r["name"] == "front.request"}
        assert set(front_spans) == {0, 1, 2}
        # adopted: the span joins the SUPPLIED trace
        assert front_spans[1]["attrs"]["trace"] == supplied[0]
        # minted at admission: a fresh well-formed id, not the garbage
        minted = front_spans[0]["attrs"]["trace"]
        assert pctx.from_wire(f"{minted}/00000000") is not None
        garbage = front_spans[2]["attrs"]["trace"]
        assert garbage not in ("complete/garbage!!!!",) and len(garbage) == 16
        assert len({minted, garbage, supplied[0]}) == 3
        # the batched flush span lists every trace id it scored
        flush_tids = set()
        for r in recs:
            if r["name"] == "serve.flush":
                flush_tids.update(r["attrs"].get("traces", ()))
        assert {minted, garbage, supplied[0]} <= flush_tids

    def test_clock_cmd_answers_ping_pong(self, tracer):
        from photon_ml_tpu.serving.frontend import (AdmissionConfig,
                                                    FrontendConfig,
                                                    ThreadedFrontend)

        pulse.configure("frontend")
        front = ThreadedFrontend(
            _engine(), config=FrontendConfig(
                admission=AdmissionConfig(budget_s=30.0),
                batcher_deadline_s=0.002)).start()
        try:
            c = _Client(front.port)
            try:
                t0 = pclock.now_ns()
                reply = c.ask({"cmd": "clock", "t0": t0})
                t3 = pclock.now_ns()
            finally:
                c.close()
        finally:
            front.stop()
        ck = reply["clock"]
        assert ck["t0"] == t0 and ck["who"] == "frontend"
        assert t0 <= ck["t1"] <= ck["t2"]
        offset, rtt = pclock.observe_exchange("frontend", ck["t0"], ck["t1"],
                                              ck["t2"], t3)
        assert rtt >= 0
        # same process, same perf_counter epoch: offset is bounded by rtt
        assert abs(offset) <= rtt
        assert "frontend" in pclock.offsets()


# ---------------------------------------------------------------------------
# the pod-slice e2e: owner (in-process) -> replica (REAL subprocess),
# plus a frontend leg, merged by tools/tracemerge.py
# ---------------------------------------------------------------------------
def _read_reply(proc, err_path, timeout=60.0):
    """One JSON line from the subprocess's stdout, with a hang guard."""

    def _err_tail():
        try:
            with open(err_path) as f:
                return f.read()[-2000:]
        except OSError:
            return "<no stderr>"

    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            raise AssertionError(
                f"replica exited early (rc {proc.returncode}): "
                f"{_err_tail()}")
        ready, _, _ = select.select([proc.stdout], [], [], 0.25)
        if ready:
            line = proc.stdout.readline()
            if line:
                return json.loads(line)
    raise AssertionError(
        f"timed out waiting for replica reply; stderr: {_err_tail()}")


class TestPodSliceTimeline:
    def test_publish_to_store_visible_merged_across_processes(
            self, tmp_path, tracer):
        from photon_ml_tpu.cli.serve import build_server
        from photon_ml_tpu.online.delta_log import DeltaLog
        from photon_ml_tpu.online.replication import (ReplicationConfig,
                                                      attach_replication)
        from tools import tracemerge

        # -- phase A: the owner, in-process under tracer A -----------------
        pulse.configure("owner")
        base_dir = _save_model_dir(str(tmp_path / "base"))
        log = DeltaLog(str(tmp_path / "owner-log"), fsync="never")
        engine, swapper = build_server(base_dir, max_batch=4, warm=False,
                                       delta_log=log, log_owner=True)
        repl = attach_replication(swapper, ReplicationConfig(),
                                  registry=engine.metrics.registry)

        # -- phase B: a REAL `serve --subscribe` replica subprocess --------
        replica_json = str(tmp_path / "replica.json")
        err_path = str(tmp_path / "replica.err")
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        # stderr to a FILE: the replica logs freely, and an undrained pipe
        # would fill and deadlock it mid-handshake
        err_f = open(err_path, "w")
        proc = subprocess.Popen(
            [sys.executable, "-u", "-m", "photon_ml_tpu.cli.serve",
             "--subscribe", f"127.0.0.1:{repl.port}",
             "--spool", str(tmp_path / "spool"), "--no-warm",
             "--trace", "--trace-out", replica_json,
             "--trace-label", "replica", "--requests", "-"],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=err_f, text=True, cwd=REPO_ROOT, env=env)
        err_f.close()
        try:
            # publish ONE delta under a minted context — the trainer's
            # per-wave pattern (trainer.py binds exactly like this)
            ctx = pctx.mint()
            dim = engine.store.coordinates["user"].dim
            with pctx.bind(ctx):
                with obs.span("online.publish", coordinate="user"):
                    identity = swapper.publish_delta(
                        "user", "user1", np.arange(dim, dtype=float))
            assert identity is not None

            # poll the replica's ring over the wire until the delta is
            # store-visible UNDER OUR TRACE ID (proves tp crossed the
            # socket and survived the mirror -> follower path)
            def store_visible():
                proc.stdin.write(json.dumps({"cmd": "trace"}) + "\n")
                proc.stdin.flush()
                trace = _read_reply(proc, err_path)
                return any(e["name"] == "online.store_visible"
                           and e.get("args", {}).get("trace") == ctx[0]
                           for e in trace.get("traceEvents", ()))

            deadline = time.monotonic() + 120.0
            while not store_visible():
                assert time.monotonic() < deadline, \
                    "replica never marked the delta store-visible"
                time.sleep(0.2)

            # a torn wire context must not break scoring on the replica
            # (trailing blank line: scoring replies are async and only
            # drain on the next line / blank line / EOF)
            proc.stdin.write(
                json.dumps(_wire_req(77, user=1, tp="xx/torn")) + "\n\n")
            proc.stdin.flush()
            reply = _read_reply(proc, err_path, timeout=120.0)  # first score compiles
            assert reply["uid"] == 77 and "score" in reply

            proc.stdin.close()          # EOF: replica drains + exports
            proc.wait(timeout=120)
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait(timeout=30)
            repl.stop()
            log.close()
        assert proc.returncode == 0, open(err_path).read()[-2000:]

        owner_trace = tracer.chrome_trace()   # label still "owner"

        # -- phase C: a frontend leg under its own tracer ------------------
        from photon_ml_tpu.serving.frontend import (AdmissionConfig,
                                                    FrontendConfig,
                                                    ThreadedFrontend)

        tracer_c = Tracer(capacity=4096, enabled=True)
        prev = obs.set_tracer(tracer_c)
        try:
            pulse.configure("frontend")
            front = ThreadedFrontend(
                _engine(), config=FrontendConfig(
                    admission=AdmissionConfig(budget_s=30.0),
                    batcher_deadline_s=0.002)).start()
            try:
                c = _Client(front.port)
                try:
                    assert "score" in c.ask(_wire_req(5))
                finally:
                    c.close()
            finally:
                front.stop()
            front_trace = tracer_c.chrome_trace()
        finally:
            obs.set_tracer(prev)

        # -- merge all three through the CLI -------------------------------
        owner_json = str(tmp_path / "owner.json")
        front_json = str(tmp_path / "front.json")
        json.dump(owner_trace, open(owner_json, "w"))
        json.dump(front_trace, open(front_json, "w"))
        merged_json = str(tmp_path / "merged.json")
        rc = tracemerge.run([owner_json, replica_json, front_json,
                             "--out", merged_json, "--quiet"])
        assert rc == 0
        merged = json.load(open(merged_json))
        other = merged["otherData"]
        assert other["reference"] == "owner"
        assert other["processes"] == {"1": "owner", "2": "replica",
                                      "3": "frontend"}
        # the replica really did measure the owner over the resume reply
        replica_raw = json.load(open(replica_json))
        assert "owner" in replica_raw["otherData"]["clock"]

        # causality on the merged, clock-aligned timeline: the owner's
        # publish span starts before the replica's store-visible instant,
        # all under ONE trace id spanning two pids
        by = spans_by_trace(merged)
        chain = by[ctx[0]]
        names = [(e["pid"], e["name"]) for e in chain]
        assert (1, "online.publish") in names
        assert (2, "online.store_visible") in names
        assert (2, "repl.client.recv") in names
        publish = next(e for e in chain if e["name"] == "online.publish")
        visible = next(e for e in chain
                       if e["name"] == "online.store_visible")
        recv = next(e for e in chain if e["name"] == "repl.client.recv")
        assert publish["ts"] <= recv["ts"] <= visible["ts"]
        # the replica adopted our trace but stamped its own hop origin
        assert visible["args"]["origin"] != ctx[1]

        # the frontend leg: front.request encloses the engine flush that
        # scored it, both under the trace minted at admission (pid 3)
        front_reqs = [e for e in merged["traceEvents"]
                      if e["name"] == "front.request" and e["pid"] == 3]
        assert front_reqs
        fr = front_reqs[0]
        tid = fr["args"]["trace"]
        flushes = [e for e in merged["traceEvents"]
                   if e["name"] == "serve.flush" and e["pid"] == 3
                   and tid in e["args"].get("traces", ())]
        assert flushes
        fl = flushes[0]
        assert fr["ts"] <= fl["ts"]
        assert fl["ts"] + fl["dur"] <= fr["ts"] + fr["dur"]
