"""photonlint v4 interprocedural-summary suite (tier-1).

Covers the layers PR 18 added on top of the v3 dataflow engine:

  1. the four summary-driven rules, each with positive AND negative
     fixtures: PL015 container-donation-taint, PL016 alias-escape,
     PL017 out-spec-rank, PL018 lock-order;
  2. the summary fixpoints themselves: escape closure over
     ``return f(...)`` chains, termination on recursion and call cycles,
     the immutable-valued-attr classifier that keeps scalar accessors
     clean;
  3. ``--diff`` incremental mode must equal a full run restricted to the
     changed files FOR THE NEW RULES too (whole-package index contract);
  4. the SARIF 2.1.0 reporter: output validates against a structural
     subset of the official schema (embedded — CI has no network),
     carries rule metadata, fingerprints, and suppression kinds.
"""

import ast
import json
import os
import subprocess
import sys
import textwrap

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from photon_ml_tpu.analysis import (analyze_source, build_rules,  # noqa: E402
                                    render_sarif, run_analysis)
from photon_ml_tpu.analysis.dataflow import (immutable_valued_attrs,  # noqa: E402
                                             infer_rank)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
HOT = "photon_ml_tpu/core/fixture.py"


def lint(src, rule=None, path=HOT):
    rules = build_rules([rule]) if rule else build_rules()
    kept, _ = analyze_source(path, textwrap.dedent(src), rules)
    return kept


def _write_pkg(tmp_path, files):
    pkg = tmp_path / "pkg"
    pkg.mkdir(exist_ok=True)
    (pkg / "__init__.py").write_text("")
    for name, src in files.items():
        (pkg / name).write_text(textwrap.dedent(src))
    return str(tmp_path)


def _run(root):
    return run_analysis([os.path.join(root, "pkg")], root=root)


def _by_rule(result, rule):
    return [v for v in result.violations if v.rule == rule]


# ---------------------------------------------------------------------------
# PL015 container-donation-taint
# ---------------------------------------------------------------------------

DONATING_HEADER = """
    import jax

    def update(buf, g):
        return buf

    fit = jax.jit(update, donate_argnums=0)
"""


class TestContainerDonationTaint:
    def test_positive_leaf_read_after_container_donated(self):
        vs = lint(DONATING_HEADER + """
    def step(w, g):
        fit((w, g), g)
        return w + 1
""", "container-donation-taint")
        assert len(vs) == 1
        assert "packed into a container" in vs[0].message
        assert "`w`" in vs[0].message

    def test_positive_container_read_after_leaf_donated(self):
        vs = lint(DONATING_HEADER + """
    def step(w, g):
        pair = (w, g)
        fit(w, g)
        return pair
""", "container-donation-taint")
        assert len(vs) == 1
        assert "holds `w`" in vs[0].message

    def test_positive_pytree_helper_aliases_leaves(self):
        vs = lint(DONATING_HEADER + """
    import jax.tree_util

    def step(params, g):
        leaves = jax.tree_util.tree_leaves(params)
        fit(leaves, g)
        return params
""", "container-donation-taint")
        assert len(vs) == 1
        assert "params" in vs[0].message

    def test_positive_constant_subscript_tracks_slot(self):
        # pair[1] is g — donating pair then reading g's slot holder is
        # covered by the container read; reading the OTHER slot through a
        # fresh unpack of the donated container is too
        vs = lint(DONATING_HEADER + """
    def step(w, g):
        pair = (w, g)
        fit(pair, g)
        return w
""", "container-donation-taint")
        assert len(vs) == 1

    def test_negative_rebind_clears_taint(self):
        assert lint(DONATING_HEADER + """
    def step(w, g):
        w = fit((w, g), g)
        return w
""", "container-donation-taint") == []

    def test_negative_unread_after_donation_is_quiet(self):
        assert lint(DONATING_HEADER + """
    def step(w, g):
        out = fit((w, g), g)
        return out
""", "container-donation-taint") == []

    def test_cross_module_donor_via_program_index(self, tmp_path):
        root = _write_pkg(tmp_path, {
            "donor.py": DONATING_HEADER,
            "user.py": """
                from pkg.donor import fit

                def step(w, g):
                    fit((w, g), g)
                    return w
            """,
        })
        vs = _by_rule(_run(root), "container-donation-taint")
        assert len(vs) == 1 and vs[0].path.endswith("user.py")


# ---------------------------------------------------------------------------
# PL016 alias-escape
# ---------------------------------------------------------------------------

STORE_MOD = """
    import threading

    class Store:
        def __init__(self, table):
            self._lock = threading.Lock()
            self._table = table

        def put(self, k, v):
            with self._lock:
                self._table[k] = v

        def view(self):
            return self._table
"""


class TestAliasEscape:
    def test_positive_accessor_warning_and_caller_error(self, tmp_path):
        root = _write_pkg(tmp_path, {
            "store.py": STORE_MOD,
            "user.py": """
                def poke(store, k, v):
                    t = store.view()
                    t[k] = v
            """,
        })
        vs = _by_rule(_run(root), "alias-escape")
        sev = {(v.path.rpartition("/")[2], v.severity) for v in vs}
        assert ("store.py", "warning") in sev   # the escape hatch
        assert ("user.py", "error") in sev      # the unlocked mutation
        err = next(v for v in vs if v.severity == "error")
        assert "_table" in err.message and "lock" in err.message.lower()

    def test_positive_escape_closes_over_return_chain(self, tmp_path):
        # grab() leaks only THROUGH view() — the program-wide fixpoint
        # must close `return self.view()` over the callee's facts
        root = _write_pkg(tmp_path, {
            "store.py": STORE_MOD + """
    def grab(self):
        return self.view()
""",
            "user.py": """
                def poke(store, k, v):
                    t = store.grab()
                    t[k] = v
            """,
        })
        vs = _by_rule(_run(root), "alias-escape")
        assert any(v.severity == "error" and v.path.endswith("user.py")
                   for v in vs)

    def test_negative_mutation_under_a_lock_is_exempt(self, tmp_path):
        root = _write_pkg(tmp_path, {
            "store.py": STORE_MOD,
            "user.py": """
                def poke(store, k, v):
                    t = store.view()
                    with store._lock:
                        t[k] = v
            """,
        })
        vs = _by_rule(_run(root), "alias-escape")
        assert all(v.severity != "error" for v in vs)

    def test_negative_rebind_kills_escaped_binding(self, tmp_path):
        root = _write_pkg(tmp_path, {
            "store.py": STORE_MOD,
            "user.py": """
                def poke(store, k, v):
                    t = store.view()
                    t = {}
                    t[k] = v
            """,
        })
        vs = _by_rule(_run(root), "alias-escape")
        assert all(v.severity != "error" for v in vs)

    def test_negative_immutable_valued_attr_accessor_is_clean(self, tmp_path):
        # _n only ever holds ints: no mutation can travel through the alias
        root = _write_pkg(tmp_path, {
            "counter.py": """
                import threading

                class Counter:
                    def __init__(self):
                        self._lock = threading.Lock()
                        self._n = 0

                    def bump(self):
                        with self._lock:
                            self._n = self._n + 1

                    def value(self):
                        return self._n
            """,
        })
        assert _by_rule(_run(root), "alias-escape") == []

    def test_fixpoint_terminates_on_recursion_and_cycles(self, tmp_path):
        # a self-recursive accessor and a two-function return cycle must
        # reach the fixpoint (bounded iteration), not hang or crash
        root = _write_pkg(tmp_path, {
            "cyclic.py": STORE_MOD + """
    def spin(self):
        return self.spin()

    def ping(self):
        return self.pong()

    def pong(self):
        return self.ping()
""",
        })
        result = _run(root)  # completes == terminates
        assert isinstance(result.violations, list)


# ---------------------------------------------------------------------------
# PL017 out-spec-rank
# ---------------------------------------------------------------------------

class TestOutSpecRank:
    def test_positive_scalar_return_under_rank1_spec(self):
        vs = lint("""
            from jax.experimental.shard_map import shard_map
            from jax.sharding import PartitionSpec as P

            def kernel(x):
                return x.sum()

            f = shard_map(kernel, mesh=MESH, in_specs=P("data"),
                          out_specs=P("data"))
        """, "out-spec-rank")
        assert len(vs) == 1
        assert "rank 0" in vs[0].message and "1 dimension" in vs[0].message

    def test_positive_rank_resolved_through_helper_call(self):
        vs = lint("""
            from jax.experimental.shard_map import shard_map
            from jax.sharding import PartitionSpec as P

            def _reduce(x):
                return x.sum()

            def kernel(x):
                return _reduce(x)

            f = shard_map(kernel, mesh=MESH, in_specs=P("data"),
                          out_specs=P("data", None))
        """, "out-spec-rank")
        assert len(vs) == 1 and "rank 0" in vs[0].message

    def test_positive_tuple_specs_pair_elementwise(self):
        vs = lint("""
            import jax.numpy as jnp
            from jax.experimental.shard_map import shard_map
            from jax.sharding import PartitionSpec as P

            def kernel(x):
                return x.sum(), jnp.zeros((4,))

            f = shard_map(kernel, mesh=MESH, in_specs=P("data"),
                          out_specs=(P("data"), P("data")))
        """, "out-spec-rank")
        # only the scalar leaf violates; the rank-1 accumulator matches
        assert len(vs) == 1 and "rank 0" in vs[0].message

    def test_negative_shorter_spec_replicates_trailing_dims(self):
        assert lint("""
            import jax.numpy as jnp
            from jax.experimental.shard_map import shard_map
            from jax.sharding import PartitionSpec as P

            def kernel(x):
                return jnp.zeros((4, 4))

            f = shard_map(kernel, mesh=MESH, in_specs=P("data"),
                          out_specs=P("data"))
        """, "out-spec-rank") == []

    def test_negative_unknown_rank_stays_quiet(self):
        assert lint("""
            from jax.experimental.shard_map import shard_map
            from jax.sharding import PartitionSpec as P

            def kernel(x):
                return x @ x.T

            f = shard_map(kernel, mesh=MESH, in_specs=P("data"),
                          out_specs=P("data", None))
        """, "out-spec-rank") == []


class TestRankInference:
    def _rank(self, expr_src, env=None):
        return infer_rank(ast.parse(expr_src, mode="eval").body, env)

    def test_literals_and_constructors(self):
        assert self._rank("1.5") == 0
        assert self._rank("jnp.zeros((4, 8))") == 2
        assert self._rank("jnp.ones((n,))") == 1
        assert self._rank("x.sum()", {"x": 3}) == 0

    def test_elementwise_and_env(self):
        assert self._rank("x + y", {"x": 2, "y": 2}) == 2
        assert self._rank("x.reshape((2, 2))") == 2
        assert self._rank("unknown_call(x)") is None


# ---------------------------------------------------------------------------
# PL018 lock-order
# ---------------------------------------------------------------------------

DEADLOCK_MOD = """
    import threading

    class Alpha:
        def __init__(self, beta):
            self._lock = threading.Lock()
            self.beta = beta

        def forward(self):
            with self._lock:
                self.beta.grab_beta()

        def poke_alpha(self):
            with self._lock:
                pass

    class Beta:
        def __init__(self, alpha):
            self._lock = threading.Lock()
            self.alpha = alpha

        def grab_beta(self):
            with self._lock:
                pass

        def backward(self):
            with self._lock:
                self.alpha.poke_alpha()
"""


class TestLockOrder:
    def test_positive_opposite_order_cycle(self, tmp_path):
        root = _write_pkg(tmp_path, {"locks.py": DEADLOCK_MOD})
        vs = _by_rule(_run(root), "lock-order")
        assert vs, "opposite-order lock paths must report a cycle"
        assert any("deadlock" in v.message for v in vs)
        assert any("Alpha._lock" in v.message and "Beta._lock" in v.message
                   for v in vs)

    def test_positive_cycle_across_modules(self, tmp_path):
        head, _, tail = DEADLOCK_MOD.partition("    class Beta:")
        root = _write_pkg(tmp_path, {
            "alpha.py": head,
            "beta.py": "\n    import threading\n\n    class Beta:" + tail,
        })
        vs = _by_rule(_run(root), "lock-order")
        assert vs and any("deadlock" in v.message for v in vs)

    def test_negative_consistent_order_is_quiet(self, tmp_path):
        # both paths take Alpha then Beta — an order, not a cycle
        root = _write_pkg(tmp_path, {"locks.py": """
            import threading

            class Alpha:
                def __init__(self, beta):
                    self._lock = threading.Lock()
                    self.beta = beta

                def forward(self):
                    with self._lock:
                        self.beta.grab_beta()

                def also_forward(self):
                    with self._lock:
                        self.beta.grab_beta()

            class Beta:
                def __init__(self):
                    self._lock = threading.Lock()

                def grab_beta(self):
                    with self._lock:
                        pass
        """})
        assert _by_rule(_run(root), "lock-order") == []

    def test_negative_builtin_and_module_calls_form_no_edges(self, tmp_path):
        # the live tree's compact() shape: `os.remove(path)` and
        # `dropped.append(...)` under a held lock must NOT resolve to the
        # program's own unique `remove`/`append` defs — if they did, the
        # reverse path through flush_log would close a bogus cycle
        root = _write_pkg(tmp_path, {"locks.py": """
            import os
            import threading

            class Fleet:
                def __init__(self, log):
                    self._lock = threading.Lock()
                    self.log = log

                def remove(self, path):
                    with self._lock:
                        self.log.flush_log()

            class Log:
                def __init__(self):
                    self._lock = threading.Lock()

                def flush_log(self):
                    with self._lock:
                        pass

                def compact(self, path, dropped):
                    with self._lock:
                        os.remove(path)
                        dropped.append(path)
        """})
        assert _by_rule(_run(root), "lock-order") == []

    def test_negative_reentrant_self_nesting_is_quiet(self, tmp_path):
        # same class, same lock: RLock re-entry must not form a self-edge
        root = _write_pkg(tmp_path, {"locks.py": """
            import threading

            class Tower:
                def __init__(self):
                    self._lock = threading.RLock()

                def outer_t(self):
                    with self._lock:
                        self.inner_t()

                def inner_t(self):
                    with self._lock:
                        pass
        """})
        assert _by_rule(_run(root), "lock-order") == []

    def test_positive_bare_acquire_forms_edges(self, tmp_path):
        # same deadlock as DEADLOCK_MOD, but Alpha.forward holds its lock
        # through bare acquire()/release() instead of a with-block — the
        # hold spans the beta call between them
        root = _write_pkg(tmp_path, {"locks.py": """
            import threading

            class Alpha:
                def __init__(self, beta):
                    self._lock = threading.Lock()
                    self.beta = beta

                def forward(self):
                    self._lock.acquire()
                    try:
                        self.beta.grab_beta()
                    finally:
                        self._lock.release()

                def poke_alpha(self):
                    self._lock.acquire()
                    self._lock.release()

            class Beta:
                def __init__(self, alpha):
                    self._lock = threading.Lock()
                    self.alpha = alpha

                def grab_beta(self):
                    with self._lock:
                        pass

                def backward(self):
                    with self._lock:
                        self.alpha.poke_alpha()
        """})
        vs = _by_rule(_run(root), "lock-order")
        assert vs, "bare acquire()/release() holds must form order edges"
        assert any("Alpha._lock" in v.message and "Beta._lock" in v.message
                   for v in vs)

    def test_positive_condition_wrapper_bare_acquire(self, tmp_path):
        # cv.acquire() on a Condition wrapping self._lock canonicalises to
        # the base lock — the cycle must name Alpha._lock, not Alpha._cv
        root = _write_pkg(tmp_path, {"locks.py": """
            import threading

            class Alpha:
                def __init__(self, beta):
                    self._lock = threading.Lock()
                    self._cv = threading.Condition(self._lock)
                    self.beta = beta

                def forward(self):
                    self._cv.acquire()
                    try:
                        self.beta.grab_beta()
                    finally:
                        self._cv.release()

                def poke_alpha(self):
                    with self._lock:
                        pass

            class Beta:
                def __init__(self, alpha):
                    self._lock = threading.Lock()
                    self.alpha = alpha

                def grab_beta(self):
                    with self._lock:
                        pass

                def backward(self):
                    with self._lock:
                        self.alpha.poke_alpha()
        """})
        vs = _by_rule(_run(root), "lock-order")
        assert vs, "Condition wrapper holds must canonicalise to the base"
        assert any("Alpha._lock" in v.message for v in vs)
        assert not any("Alpha._cv" in v.message for v in vs)

    def test_negative_release_before_call_is_quiet(self, tmp_path):
        # Alpha releases BEFORE calling into Beta — no overlap, no edge,
        # even though Beta's path comes the other way
        root = _write_pkg(tmp_path, {"locks.py": """
            import threading

            class Alpha:
                def __init__(self, beta):
                    self._lock = threading.Lock()
                    self.beta = beta

                def forward(self):
                    self._lock.acquire()
                    self._lock.release()
                    self.beta.grab_beta()

                def poke_alpha(self):
                    self._lock.acquire()
                    self._lock.release()

            class Beta:
                def __init__(self, alpha):
                    self._lock = threading.Lock()
                    self.alpha = alpha

                def grab_beta(self):
                    with self._lock:
                        pass

                def backward(self):
                    with self._lock:
                        self.alpha.poke_alpha()
        """})
        assert _by_rule(_run(root), "lock-order") == []

    def test_negative_foreign_acquire_receiver_is_quiet(self, tmp_path):
        # .acquire() on something that is not a known lock (a semaphore
        # object passed in, an attr of another object) must not register
        root = _write_pkg(tmp_path, {"locks.py": """
            import threading

            class Alpha:
                def __init__(self, beta, gate):
                    self._lock = threading.Lock()
                    self.beta = beta
                    self.gate = gate

                def forward(self):
                    self.gate.acquire()
                    self.beta.grab_beta()
                    self.gate.release()

            class Beta:
                def __init__(self, alpha):
                    self._lock = threading.Lock()
                    self.alpha = alpha

                def grab_beta(self):
                    with self._lock:
                        pass

                def backward(self):
                    with self._lock:
                        self.alpha.forward()
        """})
        assert _by_rule(_run(root), "lock-order") == []


# ---------------------------------------------------------------------------
# the immutable-valued-attr classifier
# ---------------------------------------------------------------------------

def _cls(src):
    tree = ast.parse(textwrap.dedent(src))
    return next(n for n in ast.walk(tree) if isinstance(n, ast.ClassDef))


class TestImmutableValuedAttrs:
    def test_scalar_writes_classify_immutable(self):
        got = immutable_valued_attrs(_cls("""
            class C:
                def __init__(self, n: int, name):
                    self._n = 0
                    self._name = str(name)
                    self._pair = (1, "a")
                    self._table = {}

                def bump(self):
                    self._n = self._n + 1
        """))
        assert {"_n", "_name", "_pair"} <= got
        assert "_table" not in got

    def test_any_mutable_write_disqualifies(self):
        got = immutable_valued_attrs(_cls("""
            class C:
                def __init__(self):
                    self._x = 0

                def reset(self, xs):
                    self._x = xs
        """))
        assert "_x" not in got

    def test_chain_mutation_disqualifies(self):
        got = immutable_valued_attrs(_cls("""
            class C:
                def __init__(self):
                    self._buf = ()

                def push(self, v):
                    self._buf = ()
                    self._buf.append(v)
        """))
        assert "_buf" not in got

    def test_annotated_param_write_is_immutable(self):
        got = immutable_valued_attrs(_cls("""
            from typing import Optional

            class C:
                def __init__(self, start: int, tag: Optional[str]):
                    self._start = start
                    self._tag = tag
        """))
        assert {"_start", "_tag"} <= got


# ---------------------------------------------------------------------------
# --diff equivalence for the new rules
# ---------------------------------------------------------------------------

def _git(root, *args):
    subprocess.run(["git", "-C", root, "-c", "user.email=t@t",
                    "-c", "user.name=t", *args],
                   check=True, capture_output=True, text=True)


def _cli(root, *args):
    return subprocess.run(
        [sys.executable, "-m", "tools.photonlint", "--root", root,
         "--no-baseline", "--format", "json", *args],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=300)


class TestDiffEquivalenceNewRules:
    def test_diff_matches_full_run_for_alias_escape(self, tmp_path):
        # store.py (committed, unchanged) holds the accessor; the NEW
        # user.py holds the caller-side mutation — --diff lints only
        # user.py but must still connect it through the whole-package index
        pkg = tmp_path / "photon_ml_tpu"
        pkg.mkdir()
        (pkg / "__init__.py").write_text("")
        (pkg / "store.py").write_text(textwrap.dedent(STORE_MOD))
        root = str(tmp_path)
        _git(root, "init", "-q")
        _git(root, "add", "-A")
        _git(root, "commit", "-qm", "seed")
        (pkg / "user.py").write_text(textwrap.dedent("""
            def poke(store, k, v):
                t = store.view()
                t[k] = v
        """))
        full = _cli(root, os.path.join(root, "photon_ml_tpu"))
        diff = _cli(root, "--diff", "HEAD")
        assert full.returncode == 1 and diff.returncode == 1
        full_new = json.loads(full.stdout)["new"]
        diff_new = json.loads(diff.stdout)["new"]
        want = {(v["rule"], v["path"], v["line"]) for v in full_new
                if v["path"] == "photon_ml_tpu/user.py"}
        got = {(v["rule"], v["path"], v["line"]) for v in diff_new}
        assert want and got == want
        assert any(v["rule"] == "alias-escape" for v in diff_new)
        # the unchanged accessor's warning belongs to the full run only
        assert any(v["path"] == "photon_ml_tpu/store.py" for v in full_new)
        assert all(v["path"] != "photon_ml_tpu/store.py" for v in diff_new)


# ---------------------------------------------------------------------------
# SARIF reporter
# ---------------------------------------------------------------------------

# Structural subset of the official SARIF 2.1.0 schema (oasis-tcs/
# sarif-spec Schemata/sarif-schema-2.1.0.json): required top-level shape,
# run/tool/rule metadata, result locations/fingerprints/suppressions.  CI
# has no network, so validating against the full published schema is not
# an option; this subset pins every field the reporter emits.
SARIF_SUBSET_SCHEMA = {
    "$schema": "http://json-schema.org/draft-07/schema#",
    "type": "object",
    "required": ["version", "runs"],
    "properties": {
        "$schema": {"type": "string"},
        "version": {"const": "2.1.0"},
        "runs": {
            "type": "array",
            "minItems": 1,
            "items": {
                "type": "object",
                "required": ["tool", "results"],
                "properties": {
                    "columnKind": {"enum": ["utf16CodeUnits",
                                            "unicodeCodePoints"]},
                    "tool": {
                        "type": "object",
                        "required": ["driver"],
                        "properties": {
                            "driver": {
                                "type": "object",
                                "required": ["name"],
                                "properties": {
                                    "name": {"type": "string"},
                                    "version": {"type": "string"},
                                    "informationUri": {"type": "string"},
                                    "rules": {
                                        "type": "array",
                                        "items": {
                                            "type": "object",
                                            "required": ["id"],
                                            "properties": {
                                                "id": {"type": "string"},
                                                "name": {"type": "string"},
                                            },
                                        },
                                    },
                                },
                            },
                        },
                    },
                    "results": {
                        "type": "array",
                        "items": {
                            "type": "object",
                            "required": ["message"],
                            "properties": {
                                "ruleId": {"type": "string"},
                                "ruleIndex": {"type": "integer",
                                              "minimum": 0},
                                "level": {"enum": ["none", "note",
                                                   "warning", "error"]},
                                "message": {
                                    "type": "object",
                                    "required": ["text"],
                                    "properties": {
                                        "text": {"type": "string"}},
                                },
                                "locations": {
                                    "type": "array",
                                    "items": {
                                        "type": "object",
                                        "properties": {
                                            "physicalLocation": {
                                                "type": "object",
                                                "properties": {
                                                    "artifactLocation": {
                                                        "type": "object",
                                                        "required": ["uri"],
                                                    },
                                                    "region": {
                                                        "type": "object",
                                                        "properties": {
                                                            "startLine": {
                                                                "type":
                                                                "integer",
                                                                "minimum": 1,
                                                            },
                                                            "startColumn": {
                                                                "type":
                                                                "integer",
                                                                "minimum": 1,
                                                            },
                                                        },
                                                    },
                                                },
                                            },
                                        },
                                    },
                                },
                                "partialFingerprints": {
                                    "type": "object",
                                    "additionalProperties": {
                                        "type": "string"},
                                },
                                "suppressions": {
                                    "type": "array",
                                    "items": {
                                        "type": "object",
                                        "required": ["kind"],
                                        "properties": {
                                            "kind": {"enum": ["inSource",
                                                              "external"]},
                                        },
                                    },
                                },
                            },
                        },
                    },
                },
            },
        },
    },
}


class TestSarifReporter:
    def _result(self, tmp_path, src):
        pkg = tmp_path / "photon_ml_tpu"
        pkg.mkdir(exist_ok=True)
        (pkg / "__init__.py").write_text("")
        (pkg / "mod.py").write_text(textwrap.dedent(src))
        return run_analysis([str(pkg)], root=str(tmp_path))

    def test_output_validates_against_schema(self, tmp_path):
        import jsonschema

        result = self._result(tmp_path, """
            import time

            async def handler():
                time.sleep(0.1)

            def fine():  # photonlint: disable=blocking-in-async -- n/a
                return 1
        """)
        doc = json.loads(render_sarif(result.violations, [], [], result))
        jsonschema.validate(doc, SARIF_SUBSET_SCHEMA)

    def test_rule_indices_fingerprints_and_levels(self, tmp_path):
        result = self._result(tmp_path, """
            import time

            async def handler():
                time.sleep(0.1)
        """)
        assert result.violations
        doc = json.loads(render_sarif(result.violations, [], [], result))
        run = doc["runs"][0]
        rules = run["tool"]["driver"]["rules"]
        assert rules[0]["id"] == "PL000"  # parse failures upload too
        ids = [r["id"] for r in rules]
        for res in run["results"]:
            assert ids[res["ruleIndex"]] == res["ruleId"]
            assert res["partialFingerprints"]["photonlint/v1"]
            region = res["locations"][0]["physicalLocation"]["region"]
            assert region["startLine"] >= 1 and region["startColumn"] >= 1

    def test_suppression_kinds(self, tmp_path):
        result = self._result(tmp_path, """
            import time

            async def a_handler():
                time.sleep(0.1)

            async def b_handler():
                # photonlint: disable=blocking-in-async -- fixture reason
                time.sleep(0.1)
        """)
        # route the unsuppressed finding through the BASELINED channel
        doc = json.loads(render_sarif([], result.violations, [], result))
        kinds = {s["kind"] for res in doc["runs"][0]["results"]
                 for s in res.get("suppressions", [])}
        assert kinds == {"external", "inSource"}

    def test_cli_format_sarif(self, tmp_path):
        pkg = tmp_path / "photon_ml_tpu"
        pkg.mkdir()
        (pkg / "__init__.py").write_text("")
        (pkg / "mod.py").write_text(
            "import time\n\n\nasync def handler():\n    time.sleep(0.1)\n")
        proc = subprocess.run(
            [sys.executable, "-m", "tools.photonlint", "--root",
             str(tmp_path), "--no-baseline", "--format", "sarif"],
            cwd=REPO_ROOT, capture_output=True, text=True, timeout=300)
        assert proc.returncode == 1  # findings still gate the exit code
        doc = json.loads(proc.stdout)
        assert doc["version"] == "2.1.0"
        assert doc["runs"][0]["tool"]["driver"]["name"] == "photonlint"
        assert doc["runs"][0]["results"]


# ---------------------------------------------------------------------------
# registration + accounting
# ---------------------------------------------------------------------------

class TestV4Registration:
    def test_new_rules_are_registered(self):
        from photon_ml_tpu.analysis import registered_rules
        registry = registered_rules()
        codes = {cls.code for cls in registry.values()}
        assert {"PL015", "PL016", "PL017", "PL018"} <= codes

    def test_summary_cost_is_accounted(self, tmp_path):
        from photon_ml_tpu.analysis import render_json
        pkg = tmp_path / "photon_ml_tpu"
        pkg.mkdir()
        (pkg / "__init__.py").write_text("")
        (pkg / "mod.py").write_text(textwrap.dedent(STORE_MOD))
        result = run_analysis([str(pkg)], root=str(tmp_path))
        assert result.summaries_s >= 0.0
        payload = json.loads(render_json([], [], [], result))
        assert payload["summary"]["summaries_s"] >= 0.0


# ---------------------------------------------------------------------------
# summary cache (digest-keyed skip of unchanged modules)
# ---------------------------------------------------------------------------

class TestSummaryCache:
    @pytest.fixture(autouse=True)
    def _fresh_caches(self):
        # caches key on RELPATH + digest, and every _write_pkg tree shares
        # `pkg/__init__.py` with identical content — an earlier test in the
        # same process would legitimately pre-seed a hit; start empty so
        # the counts below are exact regardless of suite order
        from photon_ml_tpu.analysis import dataflow, program_index
        program_index._PARSE_CACHE.clear()
        dataflow._SUMMARY_CACHE.clear()
        yield
        program_index._PARSE_CACHE.clear()
        dataflow._SUMMARY_CACHE.clear()

    def test_second_run_hits_cache_and_edit_invalidates(self, tmp_path):
        """Unchanged sources skip the interprocedural summary pass on the
        next run (digest + parse-tree identity both match); an edited
        module re-summarises alone while its neighbours stay cached."""
        root = _write_pkg(tmp_path, {
            "a.py": """
                def f(x):
                    return x + 1
            """,
            "b.py": """
                def g(y):
                    return y * 2
            """,
        })
        first = _run(root)
        assert first.summaries_cached == 0  # never seen these paths
        n_modules = first.files_scanned

        second = _run(root)
        assert second.summaries_cached == n_modules
        assert second.violations == first.violations

        # an edit flips the digest: ONLY that module re-summarises
        (tmp_path / "pkg" / "a.py").write_text(
            "def f(x):\n    return x - 1\n")
        third = _run(root)
        assert third.summaries_cached == n_modules - 1

    def test_cached_count_rides_json_report(self, tmp_path):
        from photon_ml_tpu.analysis import render_json
        root = _write_pkg(tmp_path, {"m.py": "def h(z):\n    return z\n"})
        _run(root)
        result = _run(root)
        payload = json.loads(render_json([], [], [], result))
        assert payload["summary"]["summaries_cached"] == \
            result.summaries_cached > 0
