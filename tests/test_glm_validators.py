"""Composable GLM model validators over every task type.

Reference analog (SURVEY §4): photon-api integTest supervised/* — train simple
GLMs and assert SEMANTIC properties via composable validators
(PredictionFiniteValidator, BinaryPredictionValidator,
BinaryClassifierAUCValidator, NonNegativePredictionValidator,
MaximumDifferenceValidator, CompositeModelValidator — BaseGLMIntegTest.scala
runs the composition per task).  Here the validators are small functions
composed per task, and the "distributed vs local" MaximumDifference check
compares the 8-device-mesh solve against the single-device solve.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu.core import GLMObjective, Regularization, losses
from photon_ml_tpu.core.batch import dense_batch
from photon_ml_tpu.opt import SolverConfig, make_solver
from photon_ml_tpu.parallel import fit_fixed_effect, make_mesh
from photon_ml_tpu.types import TaskType

D = 6


# --- validators (each: (task, w, x, scores, means) -> None, raises on fail) --

def prediction_finite(task, w, x, scores, means, **_):
    """PredictionFiniteValidator: every prediction is finite."""
    assert np.all(np.isfinite(means)), task


def binary_prediction(task, w, x, scores, means, **_):
    """BinaryPredictionValidator: thresholded means fall in {0, 1} and both
    classes actually occur on a balanced problem."""
    preds = (means > 0.5).astype(float)
    assert set(np.unique(preds)) <= {0.0, 1.0}
    assert 0.1 < preds.mean() < 0.9, task


def classifier_auc(threshold):
    def _check(task, w, x, scores, means, y=None, **_):
        from photon_ml_tpu.evaluation.metrics import auc_roc

        auc = float(auc_roc(jnp.asarray(scores), jnp.asarray(y),
                            jnp.ones(len(y))))
        assert auc > threshold, (task, auc)
    return _check


def non_negative_prediction(task, w, x, scores, means, **_):
    """NonNegativePredictionValidator (Poisson: exp mean > 0)."""
    assert np.all(means >= 0), task


def max_difference(tol):
    """MaximumDifferenceValidator: distributed (8-device mesh) vs local solve
    coefficients agree within tol — the reference's distributed-vs-local
    semantic bar."""
    def _check(task, w, x, scores, means, w_local=None, **_):
        assert np.max(np.abs(np.asarray(w) - np.asarray(w_local))) < tol, task
    return _check


_LOSS = {
    TaskType.LOGISTIC_REGRESSION: losses.logistic_loss,
    TaskType.LINEAR_REGRESSION: losses.squared_loss,
    TaskType.POISSON_REGRESSION: losses.poisson_loss,
    TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM: losses.smoothed_hinge_loss,
}

_VALIDATORS = {  # CompositeModelValidator per task (BaseGLMIntegTest pattern)
    TaskType.LOGISTIC_REGRESSION: [prediction_finite, binary_prediction,
                                   classifier_auc(0.8), max_difference(5e-3)],
    TaskType.LINEAR_REGRESSION: [prediction_finite, max_difference(5e-3)],
    TaskType.POISSON_REGRESSION: [prediction_finite, non_negative_prediction,
                                  max_difference(5e-3)],
    TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM: [prediction_finite,
                                              classifier_auc(0.8),
                                              max_difference(5e-3)],
}


def _data_for(task, rng, n=800):
    x = rng.normal(size=(n, D))
    w_true = rng.normal(size=D) * 0.7
    z = x @ w_true
    if task == TaskType.LINEAR_REGRESSION:
        y = z + rng.normal(size=n) * 0.3
    elif task == TaskType.POISSON_REGRESSION:
        y = rng.poisson(np.exp(np.clip(z * 0.5, -4, 3))).astype(float)
    else:
        y = (rng.random(n) < 1.0 / (1.0 + np.exp(-z))).astype(float)
    return x.astype(np.float32), y.astype(np.float32)


@pytest.mark.parametrize("task", list(_VALIDATORS))
def test_glm_semantic_validators(task, rng, devices):
    x, y = _data_for(task, rng)
    batch = dense_batch(x, y)
    obj = GLMObjective(loss=_LOSS[task], reg=Regularization(l2=1.0))
    cfg = SolverConfig(max_iters=60, tolerance=1e-8)

    # local (single-device) and distributed (8-device mesh) solves
    w_local = jax.jit(make_solver(obj, config=cfg))(jnp.zeros(D, jnp.float32),
                                                    batch).w
    w_dist = fit_fixed_effect(obj, batch, jnp.zeros(D, jnp.float32),
                              make_mesh(n_data=8, devices=devices),
                              config=cfg).w

    scores = np.asarray(x @ np.asarray(w_dist))
    means = np.asarray(_LOSS[task].mean(jnp.asarray(scores)))

    for validator in _VALIDATORS[task]:
        validator(task, w_dist, x, scores, means, y=y, w_local=w_local)
