"""Property tests: L-BFGS/OWLQN on random convex quadratics.

A strongly-convex quadratic has a closed-form optimum, so the solver core
(two-loop recursion, strong-Wolfe line search, box projection) can be
checked against exact answers on randomly-conditioned problems — breadth
the scipy-parity tests in test_optimizers (fixed problems) don't give.
One fixed shape keeps a single jit compile across all hypothesis examples.
"""

import os
import sys

import numpy as np
import pytest

pytest.importorskip("hypothesis")  # not in the image; skip, don't error at collection
from hypothesis import given, settings
from hypothesis import strategies as st

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from photon_ml_tpu.opt.lbfgs import minimize_lbfgs  # noqa: E402
from photon_ml_tpu.opt.types import SolverConfig  # noqa: E402

_D = 5


def _quad_vg(A, b):
    def vg(w):
        g = A @ w - b
        return 0.5 * jnp.vdot(w, A @ w) - jnp.vdot(b, w), g
    return vg


@jax.jit
def _solve_quad(A, b, w0):
    return minimize_lbfgs(_quad_vg(A, b), w0,
                          SolverConfig(max_iters=100, tolerance=1e-12))


@jax.jit
def _solve_quad_box(A, b, w0, lo, hi):
    return minimize_lbfgs(_quad_vg(A, b), w0,
                          SolverConfig(max_iters=200, tolerance=1e-12),
                          box=(lo, hi))


def _spd(draw_mat, jitter):
    M = np.asarray(draw_mat, np.float64).reshape(_D, _D)
    return M @ M.T + jitter * np.eye(_D)


_mat = st.lists(st.floats(-2, 2, allow_nan=False),
                min_size=_D * _D, max_size=_D * _D)
_vec = st.lists(st.floats(-3, 3, allow_nan=False),
                min_size=_D, max_size=_D).map(
                    lambda v: np.asarray(v, np.float64))


@settings(max_examples=50, deadline=None)
@given(m=_mat, b=_vec, w0=_vec, jitter=st.floats(0.1, 5.0))
def test_lbfgs_reaches_analytic_optimum(m, b, w0, jitter):
    A = _spd(m, jitter)
    res = _solve_quad(jnp.asarray(A), jnp.asarray(b), jnp.asarray(w0))
    want = np.linalg.solve(A, b)
    np.testing.assert_allclose(np.asarray(res.w), want, rtol=1e-5, atol=1e-6)


@settings(max_examples=40, deadline=None)
@given(m=_mat, b=_vec, w0=_vec, jitter=st.floats(0.5, 5.0))
def test_box_constrained_satisfies_kkt(m, b, w0, jitter):
    """Projected L-BFGS on a box: the result must (a) lie inside the box and
    (b) satisfy the projected-gradient stationarity condition
    ||w - P(w - g)|| ~ 0 — the exact KKT certificate the solver's own
    convergence test uses, verified here from scratch in numpy."""
    A = _spd(m, jitter)
    lo, hi = np.full(_D, -0.5), np.full(_D, 0.5)
    res = _solve_quad_box(jnp.asarray(A), jnp.asarray(b),
                          jnp.asarray(np.clip(w0, lo, hi)),
                          jnp.asarray(lo), jnp.asarray(hi))
    w = np.asarray(res.w)
    assert np.all(w >= lo - 1e-9) and np.all(w <= hi + 1e-9)
    g = A @ w - b
    proj_g = w - np.clip(w - g, lo, hi)
    np.testing.assert_allclose(proj_g, 0.0, atol=5e-5)


@jax.jit
def _solve_quad_tron(A, b, w0):
    from photon_ml_tpu.opt.tron import minimize_tron

    return minimize_tron(_quad_vg(A, b), lambda w, v: A @ v, w0,
                         SolverConfig(max_iters=30, tolerance=1e-12))


@settings(max_examples=40, deadline=None)
@given(m=_mat, b=_vec, w0=_vec, jitter=st.floats(0.1, 5.0))
def test_tron_reaches_analytic_optimum(m, b, w0, jitter):
    """TRON (trust region + truncated CG) on the same random quadratics:
    with an exact quadratic model the solver must land on the closed-form
    optimum — any trust-region/CG bookkeeping slip shows up immediately."""
    A = _spd(m, jitter)
    res = _solve_quad_tron(jnp.asarray(A), jnp.asarray(b), jnp.asarray(w0))
    want = np.linalg.solve(A, b)
    np.testing.assert_allclose(np.asarray(res.w), want, rtol=1e-5, atol=1e-5)
