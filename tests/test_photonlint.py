"""photonlint test suite (tier-1).

Four layers:
  1. per-rule positive/negative fixtures — each rule must flag its hazard
     and stay quiet on the idiomatic-correct twin;
  2. framework behaviour — suppression comments, baseline round-trip +
     --prune-baseline, parse-error surfacing, jit-index idiom resolution;
  3. whole-program resolution — a two-module fixture package where the
     jitted function and the violation live in different modules must be
     flagged with the ProgramIndex on and stay clean with
     ``--no-program-index``, incremental ``--paths`` runs must match the
     full run, and PL007 must see through the real repo's axis-name
     indirections (parallel/fixed.py against a shrunk mesh universe);
  4. the GATE: the full rule suite over ``photon_ml_tpu/`` must produce
     zero non-baselined violations and zero stale baseline entries (this
     is what makes every future PR lint-clean by construction), plus a CLI
     smoke test so ``python -m tools.photonlint`` and this test cannot
     drift apart.
"""

import json
import os
import subprocess
import sys
import textwrap

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from photon_ml_tpu.analysis import (analyze_source, build_rules,  # noqa: E402
                                    load_baseline, make_baseline, partition,
                                    registered_rules, run_analysis,
                                    save_baseline)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG_DIR = os.path.join(REPO_ROOT, "photon_ml_tpu")
BASELINE_PATH = os.path.join(REPO_ROOT, "photonlint_baseline.json")
HOT = "photon_ml_tpu/core/fixture.py"  # relpath inside dtype rule's scope


def lint(src, rule=None, path=HOT):
    rules = build_rules([rule]) if rule else build_rules()
    kept, _ = analyze_source(path, textwrap.dedent(src), rules)
    return kept


def suppressed(src, rule=None, path=HOT):
    rules = build_rules([rule]) if rule else build_rules()
    _, supp = analyze_source(path, textwrap.dedent(src), rules)
    return supp


# -- PL001 host-sync ---------------------------------------------------------

class TestHostSync:
    def test_positive_item_and_np_asarray_inside_jit(self):
        vs = lint("""
            import jax
            import numpy as np

            @jax.jit
            def f(x):
                y = x.item()
                return np.asarray(y)
        """, "host-sync")
        assert len(vs) == 2
        assert all(v.rule == "host-sync" for v in vs)

    def test_positive_float_cast_of_param(self):
        vs = lint("""
            import jax

            @jax.jit
            def f(x):
                return float(x)
        """, "host-sync")
        assert len(vs) == 1 and "concretizes" in vs[0].message

    def test_positive_tolist_in_jit_wrapped_by_name(self):
        vs = lint("""
            import jax

            def solve(w):
                return w.tolist()

            fit = jax.jit(solve)
        """, "host-sync")
        assert len(vs) == 1 and ".tolist()" in vs[0].message

    def test_positive_print_of_param_is_warning(self):
        vs = lint("""
            import jax

            @jax.jit
            def f(x):
                print(x)
                return x
        """, "host-sync")
        assert len(vs) == 1 and vs[0].severity == "warning"

    def test_negative_outside_jit(self):
        assert lint("""
            import numpy as np

            def host_stats(x):
                return float(np.asarray(x).sum()), x.item()
        """, "host-sync") == []

    def test_negative_jnp_asarray_and_static_float(self):
        assert lint("""
            import jax
            import jax.numpy as jnp

            @jax.jit
            def f(x):
                n = x.shape[0]
                return jnp.asarray(x) * float(n)
        """, "host-sync") == []


# -- PL002 recompile-hazard --------------------------------------------------

class TestRecompileHazard:
    def test_positive_jit_in_loop(self):
        vs = lint("""
            import jax

            def sweep(fns, x):
                outs = []
                for fn in fns:
                    outs.append(jax.jit(fn))
                return outs
        """, "recompile-hazard")
        assert len(vs) == 1 and "inside a loop" in vs[0].message

    def test_positive_immediately_invoked_jit(self):
        vs = lint("""
            import jax

            def score(f, x):
                return jax.jit(f)(x)
        """, "recompile-hazard")
        assert len(vs) == 1 and "fresh" in vs[0].message

    def test_positive_dynamic_static_spec(self):
        vs = lint("""
            import jax

            def build(f, nums):
                return jax.jit(f, static_argnums=nums)
        """, "recompile-hazard")
        assert len(vs) == 1 and "static_argnums" in vs[0].message

    def test_negative_module_level_and_comprehension(self):
        # the build-once setup idioms of parallel/multihost.py
        assert lint("""
            import jax

            def f(x):
                return x

            g = jax.jit(f)
            table = {k: jax.jit(f, static_argnames=("n",)) for k in range(3)}
        """, "recompile-hazard") == []

    def test_negative_aot_bind_then_compile(self):
        # serving/engine.py: construct once per cache miss, then cache
        assert lint("""
            import jax

            def build(fn, args):
                jitted = jax.jit(fn)
                return jitted.lower(*args).compile()
        """, "recompile-hazard") == []


# -- PL003 tracer-safety -----------------------------------------------------

class TestTracerSafety:
    def test_positive_if_on_param(self):
        vs = lint("""
            import jax

            @jax.jit
            def f(x):
                if x > 0:
                    return x
                return -x
        """, "tracer-safety")
        assert len(vs) == 1 and "lax.cond" in vs[0].message

    def test_positive_while_and_iteration(self):
        vs = lint("""
            import jax

            @jax.jit
            def f(x):
                while x > 0:
                    x = x - 1
                for row in x:
                    pass
                return x
        """, "tracer-safety")
        assert {v.message.split()[0] for v in vs} == {"Python", "iterating"}

    def test_positive_ternary_and_assert(self):
        vs = lint("""
            import jax

            @jax.jit
            def f(x, y):
                assert y > 0
                return x if y > 0 else -x
        """, "tracer-safety")
        sev = sorted(v.severity for v in vs)
        assert sev == ["error", "warning"]

    def test_negative_static_tests(self):
        assert lint("""
            import jax

            @jax.jit
            def f(x, w=None):
                if w is None:
                    w = x
                if x.shape[0] > 2 and len(x) > 2:
                    w = w + 1
                return w
        """, "tracer-safety") == []

    def test_negative_static_argnames_param_exempt(self):
        assert lint("""
            import functools
            import jax

            @functools.partial(jax.jit, static_argnames=("n",))
            def f(x, n):
                if n > 2:
                    return x * n
                return x
        """, "tracer-safety") == []


# -- PL004 dtype-discipline --------------------------------------------------

class TestDtypeDiscipline:
    def test_positive_f64_dtype_kwarg_and_attr(self):
        vs = lint("""
            import jax.numpy as jnp
            import numpy as np

            def init(n):
                a = jnp.zeros(n, dtype=np.float64)
                b = jnp.asarray([1.0], "float64")
                return a.astype(jnp.float64) + b
        """, "dtype-discipline")
        assert len(vs) == 3

    def test_positive_np_math_on_tracer(self):
        vs = lint("""
            import jax
            import numpy as np

            @jax.jit
            def f(x):
                return np.exp(x)
        """, "dtype-discipline")
        assert len(vs) == 1 and "jnp.exp" in vs[0].message

    def test_negative_host_numpy_f64_outside_jit(self):
        # normalization-statistics idiom: f64 accumulation is host-side
        assert lint("""
            import numpy as np

            def stats(values):
                return np.asarray(values, np.float64).sum()
        """, "dtype-discipline") == []

    def test_negative_out_of_scope_path(self):
        # storage codecs are host-side: f64 is the on-disk precision there
        assert lint("""
            import jax.numpy as jnp
            import numpy as np

            x = jnp.zeros(3, dtype=np.float64)
        """, "dtype-discipline",
                    path="photon_ml_tpu/storage/fixture.py") == []

    def test_negative_dtype_following(self):
        assert lint("""
            import jax.numpy as jnp

            def f(x):
                return jnp.zeros(x.shape, x.dtype)
        """, "dtype-discipline") == []


# -- PL005 lock-discipline ---------------------------------------------------

class TestLockDiscipline:
    def test_positive_unlocked_mutation_of_locked_attr(self):
        vs = lint("""
            import threading

            class Counter:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.n = 0

                def safe(self):
                    with self._lock:
                        self.n += 1

                def racy(self):
                    self.n += 1
        """, "lock-discipline")
        assert len(vs) == 1 and "data race" in vs[0].message
        assert vs[0].line == 14  # the mutation in racy()

    def test_positive_mutation_after_release(self):
        vs = lint("""
            import threading

            class Cache:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.entries = {}
                    self.count = 0

                def put(self, k, v):
                    with self._lock:
                        self.entries[k] = v
                    self.count += 1
        """, "lock-discipline")
        assert len(vs) == 1 and "outside it" in vs[0].message

    def test_negative_all_mutations_locked(self):
        assert lint("""
            import threading

            class Counter:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.n = 0
                    self.items = []

                def bump(self):
                    with self._lock:
                        self.n += 1
                        self.items.append(self.n)
        """, "lock-discipline") == []

    def test_negative_class_without_lock(self):
        # single-threaded classes are out of scope by design
        assert lint("""
            class Accum:
                def __init__(self):
                    self.n = 0

                def bump(self):
                    self.n += 1
        """, "lock-discipline") == []

    def test_negative_init_exempt(self):
        assert lint("""
            import threading

            class C:
                def __init__(self, n):
                    self._lock = threading.Lock()
                    self.n = n

                def set(self, n):
                    with self._lock:
                        self.n = n
        """, "lock-discipline") == []

    # -- the PL005 blind spots found while building the ProgramIndex --------

    def test_positive_operator_module_mutation(self):
        vs = lint("""
            import operator
            import threading

            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.items = []

                def safe(self):
                    with self._lock:
                        self.items.append(1)

                def racy(self):
                    operator.iadd(self.items, [2])
        """, "lock-discipline")
        assert len(vs) == 1 and "data race" in vs[0].message

    def test_positive_operator_alias_setitem(self):
        vs = lint("""
            import operator as op
            import threading

            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.d = {}

                def safe(self, k, v):
                    with self._lock:
                        self.d[k] = v

                def racy(self, k, v):
                    op.setitem(self.d, k, v)
        """, "lock-discipline")
        assert len(vs) == 1

    def test_positive_from_operator_import(self):
        vs = lint("""
            import threading
            from operator import iadd

            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.items = []

                def safe(self):
                    with self._lock:
                        self.items.extend([0])

                def racy(self):
                    iadd(self.items, [1])
        """, "lock-discipline")
        assert len(vs) == 1

    def test_positive_starred_unpack_target(self):
        vs = lint("""
            import threading

            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.head = None
                    self.rest = []

                def safe(self, xs):
                    with self._lock:
                        self.head, *self.rest = xs

                def racy(self, xs):
                    self.head, *self.rest = xs
        """, "lock-discipline")
        assert len(vs) == 2  # head AND the starred rest slot

    def test_negative_operator_mutation_under_lock(self):
        assert lint("""
            import operator
            import threading

            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.items = []

                def safe(self):
                    with self._lock:
                        operator.iadd(self.items, [1])
        """, "lock-discipline") == []


# -- PL006 donation-after-use ------------------------------------------------

class TestDonation:
    def test_positive_read_after_donating_call(self):
        vs = lint("""
            import jax

            def update(buf, v):
                return buf + v

            f = jax.jit(update, donate_argnums=(0,))

            def caller(v):
                buf = make()
                out = f(buf, v)
                return buf * 2
        """, "donation-after-use")
        assert len(vs) == 1 and "use-after-free" in vs[0].message
        assert "buf" in vs[0].message and vs[0].severity == "error"

    def test_positive_donate_argnames_keyword(self):
        vs = lint("""
            import jax

            def update(buf, v):
                return buf + v

            f = jax.jit(update, donate_argnames=("buf",))

            def caller(v):
                b = make()
                out = f(buf=b, v=v)
                return b.sum()
        """, "donation-after-use")
        assert len(vs) == 1 and "`b`" in vs[0].message

    def test_positive_aot_chain_donor(self):
        # serving/engine.py's jit().lower().compile() executable idiom
        vs = lint("""
            import jax

            def kernel(buf, w):
                return buf @ w

            exe = jax.jit(kernel, donate_argnums=(0,)).lower(x, w).compile()

            def score(w):
                req = stage()
                out = exe(req, w)
                return req
        """, "donation-after-use")
        assert len(vs) == 1

    def test_positive_reuse_across_loop_iterations(self):
        vs = lint("""
            import jax

            def update(buf, v):
                return buf + v

            f = jax.jit(update, donate_argnums=(0,))

            def caller(vs):
                buf = make()
                acc = []
                for v in vs:
                    acc.append(f(buf, v))
                return acc
        """, "donation-after-use")
        assert len(vs) == 1  # iteration 2 reads the buffer donated in 1

    def test_positive_conditional_donate_spec(self):
        # engine.py's backend-gated spec: both IfExp branches contribute
        vs = lint("""
            import jax

            def update(buf, v):
                return buf + v

            donate = (0,) if accelerated else ()
            f = jax.jit(update, donate_argnums=donate)

            def caller(v):
                buf = make()
                out = f(buf, v)
                return buf
        """, "donation-after-use")
        assert len(vs) == 1

    def test_positive_param_donation_is_warning(self):
        vs = lint("""
            import jax

            def update(buf, v):
                return buf + v

            f = jax.jit(update, donate_argnums=(0,))

            def helper(buf, v):
                return f(buf, v)
        """, "donation-after-use")
        assert len(vs) == 1 and vs[0].severity == "warning"
        assert "crosses the function boundary" in vs[0].message

    def test_negative_rebind_idiom(self):
        # transfer.py's sanctioned pattern: out = donating(out, ...)
        assert lint("""
            import jax

            def update(buf, v):
                return buf + v

            f = jax.jit(update, donate_argnums=(0,))

            def caller(vs):
                buf = make()
                for v in vs:
                    buf = f(buf, v)
                return buf
        """, "donation-after-use") == []

    def test_negative_no_donation(self):
        assert lint("""
            import jax

            def update(buf, v):
                return buf + v

            f = jax.jit(update)

            def caller(v):
                buf = make()
                out = f(buf, v)
                return buf
        """, "donation-after-use") == []

    def test_negative_read_before_donate(self):
        assert lint("""
            import jax

            def update(buf, v):
                return buf + v

            f = jax.jit(update, donate_argnums=(0,))

            def caller(v):
                buf = make()
                checksum = buf.sum()
                out = f(buf, v)
                return out, checksum
        """, "donation-after-use") == []


# -- PL007 mesh-axis ----------------------------------------------------------

class TestMeshAxis:
    def test_positive_shard_map_site_mesh(self):
        vs = lint("""
            import jax
            from jax.sharding import Mesh, PartitionSpec as P

            mesh = Mesh(devices, ("data", "model"))

            def run(w, b):
                def local(w, b):
                    return jax.lax.psum(w, "batch")
                return jax.shard_map(local, mesh=mesh, in_specs=(P(), P()),
                                     out_specs=P())(w, b)
        """, "mesh-axis")
        assert len(vs) == 1
        assert "'batch'" in vs[0].message and "data" in vs[0].message

    def test_positive_universe_fallback(self):
        # no shard_map binding resolvable: validate against every Mesh in
        # the module (the --no-program-index fallback)
        vs = lint("""
            import jax
            from jax.sharding import Mesh

            mesh = Mesh(devices, ("data",))

            def local(w):
                return jax.lax.psum(w, "feature")
        """, "mesh-axis")
        assert len(vs) == 1 and "no Mesh in the program" in vs[0].message

    def test_positive_axis_via_constant_chain(self):
        # the repo idiom: axis name constant -> parameter default -> use
        vs = lint("""
            import jax
            from jax.sharding import Mesh

            ROWS = "rows"
            mesh = Mesh(devices, (ROWS,))

            class Obj:
                def __init__(self, axis="cols"):
                    self.axis = axis

                def value(self, w):
                    obj, axis = self, self.axis
                    return jax.lax.psum(w, axis)
        """, "mesh-axis")
        assert len(vs) == 1 and "'cols'" in vs[0].message

    def test_negative_valid_axes(self):
        assert lint("""
            import jax
            from jax.sharding import Mesh

            AXIS = "rows"
            mesh = Mesh(devices, (AXIS, "cols"))

            def run(w):
                def local(w):
                    i = jax.lax.axis_index(AXIS)
                    return jax.lax.psum(w, "cols") + i
                return jax.shard_map(local, mesh=mesh)(w)
        """, "mesh-axis") == []

    def test_negative_unresolvable_axis_stays_quiet(self):
        assert lint("""
            import jax
            from jax.sharding import Mesh

            mesh = Mesh(devices, ("data",))

            def run(w, axis):
                return jax.lax.psum(w, axis)
        """, "mesh-axis") == []

    def test_negative_no_mesh_anywhere(self):
        assert lint("""
            import jax

            def local(w):
                return jax.lax.psum(w, "anything")
        """, "mesh-axis") == []


# -- PL008 sharding-annotation ------------------------------------------------

PARALLEL = "photon_ml_tpu/parallel/fixture.py"


class TestShardingAnnotation:
    def test_positive_unannotated_jit_on_mesh_path(self):
        vs = lint("""
            import jax

            def solve(w, b):
                return w

            fitted = jax.jit(solve)
        """, "sharding-annotation", path=PARALLEL)
        assert len(vs) == 1 and vs[0].severity == "warning"
        assert "out_shardings" in vs[0].message

    def test_positive_unannotated_decorators(self):
        vs = lint("""
            import functools
            import jax

            @jax.jit
            def a(w):
                return w

            @functools.partial(jax.jit, static_argnames=("n",))
            def b(w, n):
                return w * n
        """, "sharding-annotation", path=PARALLEL)
        assert len(vs) == 2

    def test_negative_annotated_or_off_mesh_path(self):
        assert lint("""
            import functools
            import jax

            @functools.partial(jax.jit, out_shardings=None)
            def a(w):
                return w

            fitted = jax.jit(a, out_shardings=rep)
        """, "sharding-annotation", path=PARALLEL) == []
        # serving/ etc. never trip the annotation warning
        assert lint("""
            import jax

            fitted = jax.jit(lambda w: w)
        """, "sharding-annotation",
                    path="photon_ml_tpu/serving/fixture.py") == []

    def test_positive_namedsharding_axis_not_on_paired_mesh(self):
        vs = lint("""
            from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

            mesh = Mesh(devices, ("data", "model"))
            s = NamedSharding(mesh, P("feature"))
        """, "sharding-annotation")
        assert len(vs) == 1
        assert "'feature'" in vs[0].message and "paired" in vs[0].message

    def test_positive_bare_pspec_against_universe(self):
        vs = lint("""
            from jax.sharding import Mesh, PartitionSpec as P

            mesh = Mesh(devices, ("data",))
            spec = P("model")
        """, "sharding-annotation")
        assert len(vs) == 1 and "no Mesh in the program" in vs[0].message

    def test_negative_valid_specs_and_unresolvable(self):
        assert lint("""
            from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

            AXIS = "data"
            mesh = Mesh(devices, (AXIS, "model"))
            a = NamedSharding(mesh, P(AXIS))
            b = NamedSharding(mesh, P(("data", "model")))
            c = NamedSharding(mesh, P(tuple(mesh.axis_names)))
            d = P(AXIS, None)

            def row_spec(arr):
                return P(AXIS, *([None] * (arr.ndim - 1)))
        """, "sharding-annotation") == []


# -- whole-program (cross-module) resolution ----------------------------------

def _write_pkg(tmp_path, files):
    pkg = tmp_path / "pkg"
    pkg.mkdir(exist_ok=True)
    (pkg / "__init__.py").write_text("")
    for name, src in files.items():
        (pkg / name).write_text(textwrap.dedent(src))
    return str(tmp_path)


CROSS_HELPER = """
    def helper(x):
        return x.item()
"""

CROSS_MAIN = """
    import jax

    from pkg.helper import helper

    fit = jax.jit(helper)
"""


class TestCrossModuleResolution:
    def _run(self, root, whole_program=True, index_paths=None, paths=None):
        return run_analysis(paths or [os.path.join(root, "pkg")],
                            root=root, whole_program=whole_program,
                            index_paths=index_paths)

    def test_jitted_in_another_module_is_flagged(self, tmp_path):
        """THE tentpole acceptance fixture: function defined in helper.py,
        jitted in main.py — flagged whole-program, clean per-module."""
        root = _write_pkg(tmp_path, {"helper.py": CROSS_HELPER,
                                     "main.py": CROSS_MAIN})
        res = self._run(root)
        assert [v.rule for v in res.violations] == ["host-sync"]
        assert res.violations[0].path == "pkg/helper.py"
        assert self._run(root, whole_program=False).violations == []

    def test_module_alias_jit_target(self, tmp_path):
        root = _write_pkg(tmp_path, {
            "helper.py": CROSS_HELPER,
            "main.py": """
                import jax

                import pkg.helper as h

                fit = jax.jit(h.helper)
            """,
        })
        res = self._run(root)
        assert [v.rule for v in res.violations] == ["host-sync"]

    def test_call_graph_propagation_across_modules(self, tmp_path):
        # helper is never jitted directly — it's CALLED from jitted code in
        # another module; tracer-safety must still fire on it
        root = _write_pkg(tmp_path, {
            "helper.py": """
                def clamp(x):
                    if x > 0:
                        return x
                    return -x
            """,
            "main.py": """
                import jax

                from pkg.helper import clamp

                @jax.jit
                def entry(x):
                    return clamp(x) + 1
            """,
        })
        res = self._run(root)
        assert [v.rule for v in res.violations] == ["tracer-safety"]
        assert res.violations[0].path == "pkg/helper.py"
        assert self._run(root, whole_program=False).violations == []

    def test_incremental_paths_match_full_run(self, tmp_path):
        # lint ONLY helper.py; the jit site lives in main.py, so the
        # finding exists iff the index covers the whole package
        root = _write_pkg(tmp_path, {"helper.py": CROSS_HELPER,
                                     "main.py": CROSS_MAIN})
        helper = os.path.join(root, "pkg", "helper.py")
        full = self._run(root)
        inc = self._run(root, paths=[helper],
                        index_paths=[os.path.join(root, "pkg")])
        assert ([v.fingerprint() for v in inc.violations]
                == [v.fingerprint() for v in full.violations])
        # without the package-wide index the violation is invisible
        assert self._run(root, paths=[helper]).violations == []

    def test_cross_module_axis_constants(self, tmp_path):
        # PL007 resolves the axis constant AND the mesh through the
        # ProgramIndex: the collective and the Mesh live in different files
        root = _write_pkg(tmp_path, {
            "meshes.py": """
                from jax.sharding import Mesh

                DATA = "data"
                mesh = Mesh(devices, (DATA, "entity"))
            """,
            "obj.py": """
                import jax

                from pkg.meshes import DATA

                def local(w):
                    return jax.lax.psum(w, DATA) + jax.lax.psum(w, "feature")
            """,
        })
        res = self._run(root)
        msgs = [v.message for v in res.violations]
        assert len(msgs) == 1 and "'feature'" in msgs[0]
        # per-module mode: obj.py has no mesh in sight -> quiet
        assert self._run(root, whole_program=False).violations == []

    def test_cli_no_program_index_escape_hatch(self, tmp_path):
        root = _write_pkg(tmp_path, {"helper.py": CROSS_HELPER,
                                     "main.py": CROSS_MAIN})
        base = [sys.executable, "-m", "tools.photonlint",
                os.path.join(root, "pkg"), "--no-baseline", "--root", root]
        on = subprocess.run(base, cwd=REPO_ROOT, capture_output=True,
                            text=True, timeout=300)
        off = subprocess.run(base + ["--no-program-index"], cwd=REPO_ROOT,
                             capture_output=True, text=True, timeout=300)
        assert on.returncode == 1 and "host-sync" in on.stdout
        assert off.returncode == 0, off.stdout + off.stderr

    def test_pl007_sees_through_real_fixed_py(self):
        """Real-repo demonstration: parallel/fixed.py's psum sites resolve
        their axis names through self.feature_axis -> parameter default ->
        the FEATURE_AXIS constant imported from parallel/mesh.py.  Linted
        against a program whose meshes LACK the feature axis, those sites
        must light up; against the real package they are clean."""
        from photon_ml_tpu.analysis.program_index import ProgramIndex

        fixed_rel = "photon_ml_tpu/parallel/fixed.py"
        with open(os.path.join(REPO_ROOT, fixed_rel), encoding="utf-8") as f:
            fixed_src = f.read()
        shrunk_mesh = textwrap.dedent("""
            from jax.sharding import Mesh

            DATA_AXIS = "data"
            ENTITY_AXIS = "entity"
            FEATURE_AXIS = "feature"

            def padded_dim(d, mesh, axis=FEATURE_AXIS):
                return d

            def replicate(mesh):
                return None

            def shard_batch(batch, mesh, axis=DATA_AXIS, feature_axis=None):
                return batch

            def shard_coefficients(w, mesh, axis=FEATURE_AXIS):
                return w

            mesh = Mesh(devices, (DATA_AXIS, ENTITY_AXIS))
        """)
        program = ProgramIndex({fixed_rel: fixed_src,
                                "photon_ml_tpu/parallel/mesh.py": shrunk_mesh})
        assert program.axis_universe == {"data", "entity"}
        kept, _ = analyze_source(fixed_rel, fixed_src,
                                 build_rules(["mesh-axis"]), program=program)
        assert len(kept) >= 3  # the feature-axis psum/axis_index sites
        assert all("'feature'" in v.message for v in kept)
        # and the real package's universe keeps them clean (the gate
        # re-checks this over every rule)
        full = ProgramIndex.from_paths(
            [os.path.join(REPO_ROOT, "photon_ml_tpu")], REPO_ROOT)
        assert {"data", "entity", "feature"} <= full.axis_universe
        kept2, _ = analyze_source(fixed_rel, fixed_src,
                                  build_rules(["mesh-axis"]), program=full)
        assert kept2 == []


# -- PL009 swallowed-exception -----------------------------------------------

class TestSwallowedException:
    def test_positive_thread_target_method(self):
        vs = lint("""
            import threading

            class Worker:
                def start(self):
                    self._t = threading.Thread(target=self._run,
                                               daemon=True)
                    self._t.start()

                def _run(self):
                    while True:
                        try:
                            self.step()
                        except Exception:
                            pass
        """, "swallowed-exception")
        assert len(vs) == 1 and vs[0].rule == "swallowed-exception"
        assert "detached" in vs[0].message

    def test_positive_async_def_body(self):
        vs = lint("""
            async def pump(q):
                while True:
                    try:
                        await q.drain()
                    except Exception:
                        continue
        """, "swallowed-exception")
        assert len(vs) == 1

    def test_positive_tuple_containing_exception(self):
        vs = lint("""
            import threading

            def run():
                try:
                    work()
                except (ValueError, Exception):
                    pass

            threading.Thread(target=run).start()
        """, "swallowed-exception")
        assert len(vs) == 1

    def test_positive_bare_except(self):
        vs = lint("""
            async def loop():
                try:
                    step()
                except:
                    pass
        """, "swallowed-exception")
        assert len(vs) == 1

    def test_negative_logging_counts_as_handled(self):
        assert lint("""
            import logging
            logger = logging.getLogger(__name__)

            async def loop():
                try:
                    step()
                except Exception:
                    logger.exception("step failed")
        """, "swallowed-exception") == []

    def test_negative_metric_increment_counts_as_handled(self):
        assert lint("""
            async def loop(registry):
                try:
                    step()
                except Exception:
                    registry.inc("step_errors_total")
        """, "swallowed-exception") == []

    def test_negative_bound_name_use_counts_as_handled(self):
        assert lint("""
            async def loop(self):
                try:
                    step()
                except Exception as e:
                    self.last_error = e
        """, "swallowed-exception") == []

    def test_negative_reraise_counts_as_handled(self):
        assert lint("""
            async def loop():
                try:
                    step()
                except Exception:
                    raise
        """, "swallowed-exception") == []

    def test_negative_cleanup_only_try_exempt(self):
        assert lint("""
            async def close(writer):
                try:
                    writer.close()
                except Exception:
                    pass
        """, "swallowed-exception") == []

    def test_negative_not_a_thread_target(self):
        # same swallow, but the function runs on the request path where a
        # raise IS observed — out of scope
        assert lint("""
            def helper():
                try:
                    work()
                except Exception:
                    pass
        """, "swallowed-exception") == []

    def test_negative_narrow_except_out_of_scope(self):
        assert lint("""
            async def loop():
                try:
                    step()
                except ValueError:
                    pass
        """, "swallowed-exception") == []

    def test_suppression_comment_works(self):
        src = """
            import threading

            def run():
                try:
                    work()
                except Exception:  # photonlint: disable=swallowed-exception -- fire drill
                    pass

            threading.Thread(target=run).start()
        """
        assert lint(src, "swallowed-exception") == []
        assert len(suppressed(src, "swallowed-exception")) == 1


# -- PL010 span-discipline ----------------------------------------------------

class TestSpanDiscipline:
    def test_positive_discarded_span_call(self):
        vs = lint("""
            from photon_ml_tpu.obs.trace import span

            def f():
                span("op", bucket=64)
                work()
        """, "span-discipline")
        assert len(vs) == 1 and vs[0].rule == "span-discipline"
        assert "discarded" in vs[0].message

    def test_positive_escaping_handle(self):
        vs = lint("""
            from photon_ml_tpu.obs.trace import span

            def begin():
                h = span("op")
                return h
        """, "span-discipline")
        assert len(vs) == 1 and "escapes" in vs[0].message

    def test_positive_enter_without_exit(self):
        vs = lint("""
            from photon_ml_tpu.obs.trace import span

            def f():
                h = span("op")
                h.__enter__()
                work()
        """, "span-discipline")
        assert len(vs) == 1 and "__enter__" in vs[0].message

    def test_positive_method_call_counts(self):
        # Tracer.span via an instance is the same contract
        vs = lint("""
            def f(tracer):
                tracer.span("op")
        """, "span-discipline")
        assert len(vs) == 1 and "discarded" in vs[0].message

    def test_negative_with_block_and_as_handle(self):
        assert lint("""
            from photon_ml_tpu.obs.trace import span

            def f():
                with span("op", bucket=64):
                    work()
                with span("op2") as h:
                    h  # the handle is usable inside the block
        """, "span-discipline") == []

    def test_negative_handle_used_as_with_item(self):
        assert lint("""
            from photon_ml_tpu.obs.trace import span

            def f():
                h = span("op")
                with h:
                    work()
        """, "span-discipline") == []

    def test_negative_balanced_manual_enter_exit(self):
        assert lint("""
            from photon_ml_tpu.obs.trace import span

            def f():
                h = span("op")
                h.__enter__()
                try:
                    work()
                finally:
                    h.__exit__(None, None, None)
        """, "span-discipline") == []

    def test_negative_non_span_enter_ignored(self):
        # a lock entered manually is not a span handle — out of scope
        assert lint("""
            def f(lock):
                lock.__enter__()
                work()
        """, "span-discipline") == []

    def test_negative_provider_module_exempt(self):
        # the module DEFINING span() is the tracer implementation
        assert lint("""
            def span(name, **attrs):
                return _Span(name, attrs)

            def helper():
                s = span("x")
                return s
        """, "span-discipline") == []


# -- suppressions ------------------------------------------------------------

SUPPRESSIBLE = """
    import jax

    @jax.jit
    def f(x):
        if x > 0:  {inline}
            return x
        return -x
"""


class TestSuppressions:
    def test_same_line_disable(self):
        src = SUPPRESSIBLE.format(
            inline="# photonlint: disable=tracer-safety -- fixture")
        assert lint(src, "tracer-safety") == []
        assert len(suppressed(src, "tracer-safety")) == 1

    def test_comment_above_disable(self):
        src = """
            import jax

            @jax.jit
            def f(x):
                # photonlint: disable=tracer-safety -- reason spanning
                # a second comment line before the statement
                if x > 0:
                    return x
                return -x
        """
        assert lint(src, "tracer-safety") == []

    def test_disable_all(self):
        src = SUPPRESSIBLE.format(inline="# photonlint: disable=all")
        assert lint(src, "tracer-safety") == []

    def test_unrelated_rule_does_not_suppress(self):
        src = SUPPRESSIBLE.format(inline="# photonlint: disable=host-sync")
        assert len(lint(src, "tracer-safety")) == 1

    def test_disable_file(self):
        src = ("# photonlint: disable-file=tracer-safety\n"
               + textwrap.dedent(SUPPRESSIBLE.format(inline="")))
        assert lint(src, "tracer-safety") == []

    def test_new_rules_suppress_like_any_other(self):
        donated = """
            import jax

            def update(buf, v):
                return buf + v

            f = jax.jit(update, donate_argnums=(0,))

            def caller(v):
                buf = make()
                out = f(buf, v)
                return buf  {inline}
        """
        flagged = donated.format(inline="")
        assert len(lint(flagged, "donation-after-use")) == 1
        quiet = donated.format(
            inline="# photonlint: disable=donation-after-use -- fixture")
        assert lint(quiet, "donation-after-use") == []
        assert len(suppressed(quiet, "donation-after-use")) == 1


# -- baseline ----------------------------------------------------------------

RACY = """
    import threading

    class C:
        def __init__(self):
            self._lock = threading.Lock()
            self.n = 0
            self.m = 0

        def safe(self):
            with self._lock:
                self.n += 1
                self.m += 1

        def racy_n(self):
            self.n += 1
"""

RACY_EXTRA = """
        def racy_m(self):
            self.m += 2
"""


class TestBaseline:
    def _violations(self, extra=""):
        return lint(textwrap.dedent(RACY + extra), "lock-discipline")

    def test_round_trip_baselined_passes_new_fails(self, tmp_path):
        vs = self._violations()
        assert len(vs) == 1
        path = str(tmp_path / "baseline.json")
        save_baseline(make_baseline(vs), path)
        loaded = load_baseline(path)
        new, matched, stale = partition(vs, loaded)
        assert new == [] and len(matched) == 1 and stale == []
        # a NEW violation (different attribute) is not absorbed
        vs2 = self._violations(extra=RACY_EXTRA)
        assert len(vs2) == 2
        new2, matched2, _ = partition(vs2, loaded)
        assert len(new2) == 1 and len(matched2) == 1
        assert "m" in new2[0].snippet

    def test_stale_entries_reported(self, tmp_path):
        vs = self._violations()
        baseline = make_baseline(vs)
        baseline["entries"]["deadbeefdeadbeef"] = {"rule": "host-sync"}
        path = str(tmp_path / "baseline.json")
        save_baseline(baseline, path)
        new, matched, stale = partition(vs, load_baseline(path))
        assert new == [] and stale == ["deadbeefdeadbeef"]

    def test_fingerprint_survives_line_shift(self):
        vs1 = self._violations()
        shifted = ("# a new leading comment\n\n"
                   + textwrap.dedent(RACY))
        vs2 = lint(shifted, "lock-discipline")
        assert len(vs2) == 1
        assert vs1[0].fingerprint() == vs2[0].fingerprint()
        assert vs1[0].line != vs2[0].line

    def test_new_rules_round_trip(self, tmp_path):
        # PL007 findings baseline and re-match like any PL001-era rule
        src = """
            import jax
            from jax.sharding import Mesh

            mesh = Mesh(devices, ("data",))

            def local(w):
                return jax.lax.psum(w, "feature")
        """
        vs = lint(src, "mesh-axis")
        assert len(vs) == 1
        path = str(tmp_path / "baseline.json")
        save_baseline(make_baseline(vs), path)
        new, matched, stale = partition(vs, load_baseline(path))
        assert new == [] and len(matched) == 1 and stale == []


BAD_FIXTURE = """
import jax

@jax.jit
def f(x):
    if x > 0:
        return x
    return -x
"""


class TestPruneBaseline:
    """The --prune-baseline workflow: stale fingerprints (debt that no
    source line produces any more) FAIL the gate by default and are
    auto-removed with the flag — paid-down debt cannot silently linger."""

    def _cli(self, args):
        return subprocess.run(
            [sys.executable, "-m", "tools.photonlint"] + args,
            cwd=REPO_ROOT, capture_output=True, text=True, timeout=300)

    def test_stale_entry_fails_then_prunes(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text(BAD_FIXTURE)
        baseline = str(tmp_path / "baseline.json")
        root = str(tmp_path)
        base_args = [str(bad), "--baseline", baseline, "--root", root]
        # 1. baseline the real finding -> gate goes green
        assert self._cli(base_args + ["--write-baseline"]).returncode == 0
        assert self._cli(base_args).returncode == 0
        # 2. plant a fingerprint no source line matches
        data = json.loads(open(baseline).read())
        real_fps = set(data["entries"])
        data["entries"]["feedfacefeedface"] = {
            "rule": "tracer-safety", "code": "PL003", "path": "bad.py",
            "message": "long-gone finding", "snippet": "gone", "occurrence": 0}
        with open(baseline, "w") as f:
            json.dump(data, f)
        # 3. stale entry -> exit 1 (the default is strict)
        proc = self._cli(base_args)
        assert proc.returncode == 1 and "stale" in proc.stdout
        # 4. --prune-baseline removes it, keeps live debt, exits 0
        assert self._cli(base_args + ["--prune-baseline"]).returncode == 0
        pruned = json.loads(open(baseline).read())
        assert set(pruned["entries"]) == real_fps
        assert self._cli(base_args).returncode == 0

    def test_incremental_run_does_not_misjudge_other_files(self, tmp_path):
        # an entry for a file OUTSIDE an incremental --paths run must not
        # be reported stale (the run can't vouch for files it didn't lint)
        bad = tmp_path / "bad.py"
        bad.write_text(BAD_FIXTURE)
        clean = tmp_path / "clean.py"
        clean.write_text("x = 1\n")
        baseline = str(tmp_path / "baseline.json")
        root = str(tmp_path)
        assert self._cli([str(bad), "--baseline", baseline, "--root", root,
                          "--write-baseline"]).returncode == 0
        proc = self._cli(["--paths", str(clean), "--baseline", baseline,
                          "--root", root])
        assert proc.returncode == 0, proc.stdout + proc.stderr


# -- framework odds and ends -------------------------------------------------

class TestFramework:
    def test_parse_error_is_a_violation(self):
        vs = lint("def broken(:\n")
        assert len(vs) == 1 and vs[0].rule == "parse-error"

    def test_rule_catalog_registered(self):
        registry = registered_rules()
        assert set(registry) >= {"host-sync", "recompile-hazard",
                                 "tracer-safety", "dtype-discipline",
                                 "lock-discipline", "donation-after-use",
                                 "mesh-axis", "sharding-annotation"}
        assert len(registry) >= 8

    def test_unknown_rule_rejected(self):
        with pytest.raises(KeyError):
            build_rules(["no-such-rule"])

    def test_jit_index_resolves_vmap_sandwich(self):
        vs = lint("""
            import jax

            def kernel(w):
                return float(w)

            vk = jax.jit(jax.vmap(kernel))
        """, "host-sync")
        assert len(vs) == 1

    def test_jit_index_resolves_lambda(self):
        vs = lint("""
            import jax
            import numpy as np

            score = jax.jit(lambda w: np.asarray(w))
        """, "host-sync")
        assert len(vs) == 1


# -- the tier-1 gate ---------------------------------------------------------

class TestPackageGate:
    def test_package_has_no_new_violations(self):
        """THE gate: every future PR must keep photon_ml_tpu/ lint-clean
        (or explicitly baseline/suppress with a reason) — in whole-program
        mode, which run_analysis defaults to."""
        result = run_analysis([PKG_DIR], root=REPO_ROOT)
        assert result.whole_program  # cross-module resolution is the default
        baseline = load_baseline(BASELINE_PATH)
        new, _, stale = partition(result.violations, baseline)
        assert not new, (
            "new photonlint violations (fix, suppress with a reason, or "
            "baseline):\n" + "\n".join(v.render() for v in new))
        assert not stale, (
            "stale baseline entries (debt paid down but still recorded) — "
            f"prune with --prune-baseline: {stale}")

    def test_committed_baseline_is_empty(self):
        # the repo carries NO accepted lint debt; keep it that way
        assert load_baseline(BASELINE_PATH)["entries"] == {}

    def test_gate_scans_the_whole_package(self):
        result = run_analysis([PKG_DIR], root=REPO_ROOT)
        assert result.files_scanned >= 100  # the package, not a subset
        # the analysis-cost budget: the whole-program pass must stay a
        # pre-commit-friendly few seconds (acceptance: < 10 s on CPU);
        # index build is the new cost and must stay a fraction of that
        assert result.index_build_s < 5.0

    def test_cli_exit_zero_on_package(self):
        proc = subprocess.run(
            [sys.executable, "-m", "tools.photonlint",
             os.path.join(REPO_ROOT, "photon_ml_tpu")],
            cwd=REPO_ROOT, capture_output=True, text=True, timeout=300)
        assert proc.returncode == 0, proc.stdout + proc.stderr

    def test_cli_json_and_nonzero_on_violation(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text(textwrap.dedent("""
            import jax

            @jax.jit
            def f(x):
                if x > 0:
                    return x
                return -x
        """))
        proc = subprocess.run(
            [sys.executable, "-m", "tools.photonlint", str(bad),
             "--no-baseline", "--format", "json"],
            cwd=REPO_ROOT, capture_output=True, text=True, timeout=300)
        assert proc.returncode == 1
        payload = json.loads(proc.stdout)
        assert payload["summary"]["new"] == 1
        assert payload["new"][0]["rule"] == "tracer-safety"
        # the CI-facing summary block: per-rule/severity counts + scan costs
        summary = payload["summary"]
        assert summary["by_rule"] == {"tracer-safety": 1}
        assert summary["by_severity"] == {"error": 1}
        assert summary["files_scanned"] == 1
        assert summary["whole_program"] is True
        assert isinstance(summary["index_build_s"], float)

    def test_bench_lint_mode(self, tmp_path):
        out = tmp_path / "BENCH_LINT.json"
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO_ROOT, "bench.py"), "--lint",
             "--lint-repeats", "1", "--out", str(out)],
            cwd=REPO_ROOT, capture_output=True, text=True, timeout=300)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        payload = json.loads(out.read_text())
        assert payload["metric"] == "photonlint_full_package_wall_s"
        assert payload["files_scanned"] >= 100
        assert 0 < payload["value"] < 10  # the acceptance budget, on CPU
        assert payload["index_build_s"] < payload["value"]
        # v4: the summary-layer share is accounted beside the dataflow one
        assert 0 <= payload["summaries_s"] < payload["value"]
        assert 0 <= payload["dataflow_s"] < payload["value"]
