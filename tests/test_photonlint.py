"""photonlint test suite (tier-1).

Three layers:
  1. per-rule positive/negative fixtures — each rule must flag its hazard
     and stay quiet on the idiomatic-correct twin;
  2. framework behaviour — suppression comments, baseline round-trip,
     parse-error surfacing, jit-index idiom resolution;
  3. the GATE: the full rule suite over ``photon_ml_tpu/`` must produce
     zero non-baselined violations (this is what makes every future PR
     lint-clean by construction), plus a CLI smoke test so
     ``python -m tools.photonlint`` and this test cannot drift apart.
"""

import json
import os
import subprocess
import sys
import textwrap

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from photon_ml_tpu.analysis import (analyze_source, build_rules,  # noqa: E402
                                    load_baseline, make_baseline, partition,
                                    registered_rules, run_analysis,
                                    save_baseline)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG_DIR = os.path.join(REPO_ROOT, "photon_ml_tpu")
BASELINE_PATH = os.path.join(REPO_ROOT, "photonlint_baseline.json")
HOT = "photon_ml_tpu/core/fixture.py"  # relpath inside dtype rule's scope


def lint(src, rule=None, path=HOT):
    rules = build_rules([rule]) if rule else build_rules()
    kept, _ = analyze_source(path, textwrap.dedent(src), rules)
    return kept


def suppressed(src, rule=None, path=HOT):
    rules = build_rules([rule]) if rule else build_rules()
    _, supp = analyze_source(path, textwrap.dedent(src), rules)
    return supp


# -- PL001 host-sync ---------------------------------------------------------

class TestHostSync:
    def test_positive_item_and_np_asarray_inside_jit(self):
        vs = lint("""
            import jax
            import numpy as np

            @jax.jit
            def f(x):
                y = x.item()
                return np.asarray(y)
        """, "host-sync")
        assert len(vs) == 2
        assert all(v.rule == "host-sync" for v in vs)

    def test_positive_float_cast_of_param(self):
        vs = lint("""
            import jax

            @jax.jit
            def f(x):
                return float(x)
        """, "host-sync")
        assert len(vs) == 1 and "concretizes" in vs[0].message

    def test_positive_tolist_in_jit_wrapped_by_name(self):
        vs = lint("""
            import jax

            def solve(w):
                return w.tolist()

            fit = jax.jit(solve)
        """, "host-sync")
        assert len(vs) == 1 and ".tolist()" in vs[0].message

    def test_positive_print_of_param_is_warning(self):
        vs = lint("""
            import jax

            @jax.jit
            def f(x):
                print(x)
                return x
        """, "host-sync")
        assert len(vs) == 1 and vs[0].severity == "warning"

    def test_negative_outside_jit(self):
        assert lint("""
            import numpy as np

            def host_stats(x):
                return float(np.asarray(x).sum()), x.item()
        """, "host-sync") == []

    def test_negative_jnp_asarray_and_static_float(self):
        assert lint("""
            import jax
            import jax.numpy as jnp

            @jax.jit
            def f(x):
                n = x.shape[0]
                return jnp.asarray(x) * float(n)
        """, "host-sync") == []


# -- PL002 recompile-hazard --------------------------------------------------

class TestRecompileHazard:
    def test_positive_jit_in_loop(self):
        vs = lint("""
            import jax

            def sweep(fns, x):
                outs = []
                for fn in fns:
                    outs.append(jax.jit(fn))
                return outs
        """, "recompile-hazard")
        assert len(vs) == 1 and "inside a loop" in vs[0].message

    def test_positive_immediately_invoked_jit(self):
        vs = lint("""
            import jax

            def score(f, x):
                return jax.jit(f)(x)
        """, "recompile-hazard")
        assert len(vs) == 1 and "fresh" in vs[0].message

    def test_positive_dynamic_static_spec(self):
        vs = lint("""
            import jax

            def build(f, nums):
                return jax.jit(f, static_argnums=nums)
        """, "recompile-hazard")
        assert len(vs) == 1 and "static_argnums" in vs[0].message

    def test_negative_module_level_and_comprehension(self):
        # the build-once setup idioms of parallel/multihost.py
        assert lint("""
            import jax

            def f(x):
                return x

            g = jax.jit(f)
            table = {k: jax.jit(f, static_argnames=("n",)) for k in range(3)}
        """, "recompile-hazard") == []

    def test_negative_aot_bind_then_compile(self):
        # serving/engine.py: construct once per cache miss, then cache
        assert lint("""
            import jax

            def build(fn, args):
                jitted = jax.jit(fn)
                return jitted.lower(*args).compile()
        """, "recompile-hazard") == []


# -- PL003 tracer-safety -----------------------------------------------------

class TestTracerSafety:
    def test_positive_if_on_param(self):
        vs = lint("""
            import jax

            @jax.jit
            def f(x):
                if x > 0:
                    return x
                return -x
        """, "tracer-safety")
        assert len(vs) == 1 and "lax.cond" in vs[0].message

    def test_positive_while_and_iteration(self):
        vs = lint("""
            import jax

            @jax.jit
            def f(x):
                while x > 0:
                    x = x - 1
                for row in x:
                    pass
                return x
        """, "tracer-safety")
        assert {v.message.split()[0] for v in vs} == {"Python", "iterating"}

    def test_positive_ternary_and_assert(self):
        vs = lint("""
            import jax

            @jax.jit
            def f(x, y):
                assert y > 0
                return x if y > 0 else -x
        """, "tracer-safety")
        sev = sorted(v.severity for v in vs)
        assert sev == ["error", "warning"]

    def test_negative_static_tests(self):
        assert lint("""
            import jax

            @jax.jit
            def f(x, w=None):
                if w is None:
                    w = x
                if x.shape[0] > 2 and len(x) > 2:
                    w = w + 1
                return w
        """, "tracer-safety") == []

    def test_negative_static_argnames_param_exempt(self):
        assert lint("""
            import functools
            import jax

            @functools.partial(jax.jit, static_argnames=("n",))
            def f(x, n):
                if n > 2:
                    return x * n
                return x
        """, "tracer-safety") == []


# -- PL004 dtype-discipline --------------------------------------------------

class TestDtypeDiscipline:
    def test_positive_f64_dtype_kwarg_and_attr(self):
        vs = lint("""
            import jax.numpy as jnp
            import numpy as np

            def init(n):
                a = jnp.zeros(n, dtype=np.float64)
                b = jnp.asarray([1.0], "float64")
                return a.astype(jnp.float64) + b
        """, "dtype-discipline")
        assert len(vs) == 3

    def test_positive_np_math_on_tracer(self):
        vs = lint("""
            import jax
            import numpy as np

            @jax.jit
            def f(x):
                return np.exp(x)
        """, "dtype-discipline")
        assert len(vs) == 1 and "jnp.exp" in vs[0].message

    def test_negative_host_numpy_f64_outside_jit(self):
        # normalization-statistics idiom: f64 accumulation is host-side
        assert lint("""
            import numpy as np

            def stats(values):
                return np.asarray(values, np.float64).sum()
        """, "dtype-discipline") == []

    def test_negative_out_of_scope_path(self):
        # storage codecs are host-side: f64 is the on-disk precision there
        assert lint("""
            import jax.numpy as jnp
            import numpy as np

            x = jnp.zeros(3, dtype=np.float64)
        """, "dtype-discipline",
                    path="photon_ml_tpu/storage/fixture.py") == []

    def test_negative_dtype_following(self):
        assert lint("""
            import jax.numpy as jnp

            def f(x):
                return jnp.zeros(x.shape, x.dtype)
        """, "dtype-discipline") == []


# -- PL005 lock-discipline ---------------------------------------------------

class TestLockDiscipline:
    def test_positive_unlocked_mutation_of_locked_attr(self):
        vs = lint("""
            import threading

            class Counter:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.n = 0

                def safe(self):
                    with self._lock:
                        self.n += 1

                def racy(self):
                    self.n += 1
        """, "lock-discipline")
        assert len(vs) == 1 and "data race" in vs[0].message
        assert vs[0].line == 14  # the mutation in racy()

    def test_positive_mutation_after_release(self):
        vs = lint("""
            import threading

            class Cache:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.entries = {}
                    self.count = 0

                def put(self, k, v):
                    with self._lock:
                        self.entries[k] = v
                    self.count += 1
        """, "lock-discipline")
        assert len(vs) == 1 and "outside it" in vs[0].message

    def test_negative_all_mutations_locked(self):
        assert lint("""
            import threading

            class Counter:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.n = 0
                    self.items = []

                def bump(self):
                    with self._lock:
                        self.n += 1
                        self.items.append(self.n)
        """, "lock-discipline") == []

    def test_negative_class_without_lock(self):
        # single-threaded classes are out of scope by design
        assert lint("""
            class Accum:
                def __init__(self):
                    self.n = 0

                def bump(self):
                    self.n += 1
        """, "lock-discipline") == []

    def test_negative_init_exempt(self):
        assert lint("""
            import threading

            class C:
                def __init__(self, n):
                    self._lock = threading.Lock()
                    self.n = n

                def set(self, n):
                    with self._lock:
                        self.n = n
        """, "lock-discipline") == []


# -- suppressions ------------------------------------------------------------

SUPPRESSIBLE = """
    import jax

    @jax.jit
    def f(x):
        if x > 0:  {inline}
            return x
        return -x
"""


class TestSuppressions:
    def test_same_line_disable(self):
        src = SUPPRESSIBLE.format(
            inline="# photonlint: disable=tracer-safety -- fixture")
        assert lint(src, "tracer-safety") == []
        assert len(suppressed(src, "tracer-safety")) == 1

    def test_comment_above_disable(self):
        src = """
            import jax

            @jax.jit
            def f(x):
                # photonlint: disable=tracer-safety -- reason spanning
                # a second comment line before the statement
                if x > 0:
                    return x
                return -x
        """
        assert lint(src, "tracer-safety") == []

    def test_disable_all(self):
        src = SUPPRESSIBLE.format(inline="# photonlint: disable=all")
        assert lint(src, "tracer-safety") == []

    def test_unrelated_rule_does_not_suppress(self):
        src = SUPPRESSIBLE.format(inline="# photonlint: disable=host-sync")
        assert len(lint(src, "tracer-safety")) == 1

    def test_disable_file(self):
        src = ("# photonlint: disable-file=tracer-safety\n"
               + textwrap.dedent(SUPPRESSIBLE.format(inline="")))
        assert lint(src, "tracer-safety") == []


# -- baseline ----------------------------------------------------------------

RACY = """
    import threading

    class C:
        def __init__(self):
            self._lock = threading.Lock()
            self.n = 0
            self.m = 0

        def safe(self):
            with self._lock:
                self.n += 1
                self.m += 1

        def racy_n(self):
            self.n += 1
"""

RACY_EXTRA = """
        def racy_m(self):
            self.m += 2
"""


class TestBaseline:
    def _violations(self, extra=""):
        return lint(textwrap.dedent(RACY + extra), "lock-discipline")

    def test_round_trip_baselined_passes_new_fails(self, tmp_path):
        vs = self._violations()
        assert len(vs) == 1
        path = str(tmp_path / "baseline.json")
        save_baseline(make_baseline(vs), path)
        loaded = load_baseline(path)
        new, matched, stale = partition(vs, loaded)
        assert new == [] and len(matched) == 1 and stale == []
        # a NEW violation (different attribute) is not absorbed
        vs2 = self._violations(extra=RACY_EXTRA)
        assert len(vs2) == 2
        new2, matched2, _ = partition(vs2, loaded)
        assert len(new2) == 1 and len(matched2) == 1
        assert "m" in new2[0].snippet

    def test_stale_entries_reported(self, tmp_path):
        vs = self._violations()
        baseline = make_baseline(vs)
        baseline["entries"]["deadbeefdeadbeef"] = {"rule": "host-sync"}
        path = str(tmp_path / "baseline.json")
        save_baseline(baseline, path)
        new, matched, stale = partition(vs, load_baseline(path))
        assert new == [] and stale == ["deadbeefdeadbeef"]

    def test_fingerprint_survives_line_shift(self):
        vs1 = self._violations()
        shifted = ("# a new leading comment\n\n"
                   + textwrap.dedent(RACY))
        vs2 = lint(shifted, "lock-discipline")
        assert len(vs2) == 1
        assert vs1[0].fingerprint() == vs2[0].fingerprint()
        assert vs1[0].line != vs2[0].line


# -- framework odds and ends -------------------------------------------------

class TestFramework:
    def test_parse_error_is_a_violation(self):
        vs = lint("def broken(:\n")
        assert len(vs) == 1 and vs[0].rule == "parse-error"

    def test_five_rules_registered(self):
        registry = registered_rules()
        assert set(registry) >= {"host-sync", "recompile-hazard",
                                 "tracer-safety", "dtype-discipline",
                                 "lock-discipline"}
        assert len(registry) >= 5

    def test_unknown_rule_rejected(self):
        with pytest.raises(KeyError):
            build_rules(["no-such-rule"])

    def test_jit_index_resolves_vmap_sandwich(self):
        vs = lint("""
            import jax

            def kernel(w):
                return float(w)

            vk = jax.jit(jax.vmap(kernel))
        """, "host-sync")
        assert len(vs) == 1

    def test_jit_index_resolves_lambda(self):
        vs = lint("""
            import jax
            import numpy as np

            score = jax.jit(lambda w: np.asarray(w))
        """, "host-sync")
        assert len(vs) == 1


# -- the tier-1 gate ---------------------------------------------------------

class TestPackageGate:
    def test_package_has_no_new_violations(self):
        """THE gate: every future PR must keep photon_ml_tpu/ lint-clean
        (or explicitly baseline/suppress with a reason)."""
        result = run_analysis([PKG_DIR], root=REPO_ROOT)
        baseline = load_baseline(BASELINE_PATH)
        new, _, _ = partition(result.violations, baseline)
        assert not new, (
            "new photonlint violations (fix, suppress with a reason, or "
            "baseline):\n" + "\n".join(v.render() for v in new))

    def test_gate_scans_the_whole_package(self):
        result = run_analysis([PKG_DIR], root=REPO_ROOT)
        assert result.files_scanned >= 100  # the package, not a subset

    def test_cli_exit_zero_on_package(self):
        proc = subprocess.run(
            [sys.executable, "-m", "tools.photonlint",
             os.path.join(REPO_ROOT, "photon_ml_tpu")],
            cwd=REPO_ROOT, capture_output=True, text=True, timeout=300)
        assert proc.returncode == 0, proc.stdout + proc.stderr

    def test_cli_json_and_nonzero_on_violation(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text(textwrap.dedent("""
            import jax

            @jax.jit
            def f(x):
                if x > 0:
                    return x
                return -x
        """))
        proc = subprocess.run(
            [sys.executable, "-m", "tools.photonlint", str(bad),
             "--no-baseline", "--format", "json"],
            cwd=REPO_ROOT, capture_output=True, text=True, timeout=300)
        assert proc.returncode == 1
        payload = json.loads(proc.stdout)
        assert payload["summary"]["new"] == 1
        assert payload["new"][0]["rule"] == "tracer-safety"
